"""Measured throughput cost of the aggregate-cache audit knob.

Quantifies the other half of r4 verdict #8: each unit of
`aggregate_cache_audit` adds one full ABD quorum read per aggregate
round (the forgery-persistence side is the analytic bound + Monte Carlo
in tests/test_tag_cache.py::test_audit_persistence_bound_monte_carlo).

To isolate the protocol cost, rows store SMALL PLAIN integers and
`SumAll` runs without `nsqr` (plain integer sum) — the fold is then
microseconds, so the measured per-request delta between audit settings
is the audit's quorum-read cost, not crypto time. K defaults to 8192
(the documented operating point).

Usage: python -m benchmarks.audit_cost [--k 8192] [--audits 0 2 4 8]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time

from benchmarks.common import emit

METRIC = "SumAll requests/sec vs aggregate_cache_audit @ K stored sets"


async def run(k: int, audits: list[int], requests: int) -> list[dict]:
    from dds_tpu.http.miniserver import http_request
    from dds_tpu.run import launch
    from dds_tpu.utils.config import DDSConfig

    cfg = DDSConfig()
    cfg.replicas.endpoints = [f"replica-{i}" for i in range(4)]
    cfg.replicas.sentinent = []
    cfg.replicas.byz_quorum_size = 3
    cfg.replicas.byz_max_faults = 1
    cfg.recovery.enabled = False
    cfg.proxy.port = 0

    dep = await launch(cfg)
    out = []
    try:
        host, port = "127.0.0.1", dep.server.cfg.port
        sem = asyncio.Semaphore(64)

        async def put(i):
            async with sem:
                body = json.dumps({"contents": [i]}).encode()
                return await http_request(host, port, "POST", "/PutSet", body)

        statuses = await asyncio.gather(*(put(i) for i in range(k)))
        assert all(s == 200 for s, _ in statuses)

        target = "/SumAll?position=0"
        want = str(sum(range(k)))
        for audit in audits:
            dep.server.cfg.aggregate_cache_audit = audit
            # warm the cache + memos for this setting
            st, body = await http_request(host, port, "GET", target, timeout=120.0)
            assert st == 200 and json.loads(body)["result"] == want
            t0 = time.perf_counter()
            for _ in range(requests):
                st, _ = await http_request(host, port, "GET", target, timeout=120.0)
                assert st == 200
            per = (time.perf_counter() - t0) / requests
            out.append({"audit": audit, "req_per_sec": 1 / per, "ms": per * 1e3})
    finally:
        await dep.stop()
    return out


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--k", type=int, default=8192)
    ap.add_argument("--audits", type=int, nargs="+", default=[0, 2, 4, 8])
    ap.add_argument("--requests", type=int, default=20)
    args = ap.parse_args(argv)

    results = asyncio.run(run(args.k, args.audits, args.requests))
    base = next((r for r in results if r["audit"] == 0), results[0])
    rows = []
    for r in results:
        rows.append(
            emit(
                METRIC,
                r["req_per_sec"],
                "req/s",
                r["req_per_sec"] / base["req_per_sec"],
                audit=r["audit"],
                K=args.k,
                sumall_ms=round(r["ms"], 2),
            )
        )
    return rows


if __name__ == "__main__":
    main()
