"""Panopticon shipper overhead on a real multi-process Meridian fleet.

    python -m benchmarks.fleet_obs_overhead [--rate 80] [--duration 2]

Spawns the benchmarks/multihost_load loopback fleet TWICE — shipper off
(plain PR-8 fleet) and shipper on ([obs.fleet] enabled in every group
process, the collector + Watchtower armed in the proxy) — and drives both
with the same coordinated-omission-safe open-loop load. The record the
run exists for: telemetry is supposed to be free-ish (spool + batch off
the request path), so `overhead_pct` — the goodput cost of turning the
whole fleet-observability plane on — is the number CI watches, alongside
the collector's own census (sources seen, trees stitched, drops
accounted) scraped from `GET /fleet/metrics` to prove the plane was
actually live during the measurement, not just configured.

One `fleet obs` record lands via `benchmarks.common.emit`;
`sentry.py --check` validates its shape (exit 2 on malformed).
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from benchmarks.multihost_load import Fleet  # noqa: E402


def _fleet_stanzas(collector: str) -> tuple[str, str]:
    """(group_extra, proxy_extra) TOML arming the Panopticon plane."""
    group = f"""
[obs.fleet]
enabled = true
collector = "{collector}"
flush-interval = 0.1
"""
    proxy = """
[obs.fleet]
enabled = true
stitch-window = 0.5
"""
    return group, proxy


async def _measure(fleet: Fleet, rate: float, duration: float, keys: int,
                   zipf_s: float, seed: int):
    from dds_tpu.fabric.loadgen import OpenLoopLoad

    load = OpenLoopLoad(fleet.proxy_targets, keys=keys, zipf_s=zipf_s,
                        seed=seed, timeout=5.0)
    await load.seed()
    return await load.run(rate, duration)


async def _fleet_census(port: int) -> dict:
    """Scrape the collector's /fleet/metrics for proof-of-life numbers."""
    from dds_tpu.http.miniserver import http_request
    from dds_tpu.obs.panopticon import parse_samples

    status, body = await http_request(
        "127.0.0.1", port, "GET", "/fleet/metrics", timeout=5.0)
    if status != 200:
        raise RuntimeError(f"GET /fleet/metrics -> {status}")
    text = body.decode() if isinstance(body, (bytes, bytearray)) else str(body)
    sources = parse_samples(text, "dds_fleet_sources")
    stitched = parse_samples(text, "dds_fleet_traces_stitched_total")
    dropped = parse_samples(text, "dds_fleet_ship_dropped_by_source")
    return {
        "sources": int(sources[0][1]) if sources else 0,
        "stitched": int(sum(v for _, v in stitched)),
        "dropped": int(sum(v for _, v in dropped)),
    }


def _run_one(shipper_on: bool, rate: float, duration: float, keys: int,
             zipf_s: float, seed: int):
    with tempfile.TemporaryDirectory(prefix="fleet-obs-") as workdir:
        fleet = Fleet(workdir)
        if shipper_on:
            # ports exist after __init__; arm the stanzas before start()
            # writes the configs — the groups ship at the proxy's TcpNet
            fleet.group_extra, fleet.proxy_extra = _fleet_stanzas(
                fleet.proxy_transport)
        census = {}
        try:
            fleet.start()
            asyncio.run(fleet.wait_healthy())
            report = asyncio.run(
                _measure(fleet, rate, duration, keys, zipf_s, seed))
            if shipper_on:
                # settle one stitch window so shipped trees land, then
                # prove the plane was live during the run
                asyncio.run(asyncio.sleep(1.0))
                census = asyncio.run(
                    _fleet_census(fleet.ports["proxy"][0]))
        finally:
            fleet.stop()
        return report, census, len(fleet.gids) + len(fleet.ports["proxy"])


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rate", type=float, default=80.0,
                    help="open-loop arrival rate (req/s)")
    ap.add_argument("--duration", type=float, default=2.0)
    ap.add_argument("--keys", type=int, default=32)
    ap.add_argument("--zipf", type=float, default=1.1)
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args(argv)

    from benchmarks.common import emit

    off, _, _ = _run_one(False, args.rate, args.duration, args.keys,
                         args.zipf, args.seed)
    on, census, procs = _run_one(True, args.rate, args.duration, args.keys,
                                 args.zipf, args.seed)

    off_good = max(1, off.good)
    overhead = 1.0 - (on.good / off_good)
    return [emit(
        "fleet obs",
        on.good / max(args.duration, 1e-9),
        "req/s",
        on.good / off_good,
        rate=args.rate,
        duration=args.duration,
        processes=procs,
        open_loop=True,
        on_good=on.good,
        off_good=off.good,
        overhead_pct=round(overhead * 100.0, 2),
        on_p95_ms=round(on.p95_ms, 3),
        off_p95_ms=round(off.p95_ms, 3),
        sources=census.get("sources", 0),
        stitched=census.get("stitched", 0),
        dropped=census.get("dropped", 0),
    )]


if __name__ == "__main__":
    main()
