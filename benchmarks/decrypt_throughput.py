"""Decrypt throughput: per-op host vs batched-CRT host vs Sanctum device.

The decrypt half of the north star's "modular exponentiations behind
encrypt, decrypt", measured across the three postures a deployment can
run (DEPLOY.md "Secret-material trust boundary (Sanctum)"):

- per-op:        `PaillierKey.decrypt` in a loop — the reference's
                 `decryptFully` shape (one CRT pair per ciphertext on the
                 per-key host plan);
- batched host:  `decrypt_batch` on the host plan — shared per-key
                 constants, native CIOS batch legs (the CRT-Paillier
                 paper's precomputation-heavy host variant);
- Sanctum device: `decrypt_batch(backend=SecretBackend(device=True))` —
                 both half-width CRT legs fused into ONE batched device
                 dispatch with the persistent compile cache bypassed.

Every path is decrypt-VERIFIED against the known plaintexts before any
timing: a fast wrong decrypt is not a result. One record per key size
via common.emit(); vs_baseline = Sanctum device over per-op host.
benchmarks/sentry.py --check validates the emitted `decrypt throughput`
records (exit 2 on malformed).

Usage: python -m benchmarks.decrypt_throughput
           [--bits 1024,2048] [--b 256] [--repeats 3]
"""

from __future__ import annotations

import argparse

import numpy as np

from benchmarks.common import best_of, emit


def _metric(bits: int) -> str:
    return f"decrypt throughput (CRT-Paillier, {bits}-bit)"


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--bits", default="1024,2048",
                    help="comma-separated Paillier modulus sizes")
    ap.add_argument("--b", type=int, default=256, help="ciphertext batch")
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args(argv)

    from dds_tpu.bench_key import bench_paillier_key
    from dds_tpu.sanctum import SecretBackend, plan_for

    rows = []
    B = args.b
    for bits in [int(x) for x in str(args.bits).split(",") if x]:
        key = bench_paillier_key(bits)
        pk = key.public
        rng = np.random.default_rng(17 + bits)
        ms = [int(x) for x in rng.integers(0, 1 << 48, size=B)]
        # a small rotating blind pool keeps ciphertext setup cheap at
        # 2048 bits without weakening anything a DECRYPT bench measures
        blinds = [pk.blind() for _ in range(16)]
        cts = [pk.encrypt(m, rn=blinds[i % 16]) for i, m in enumerate(ms)]

        dev = SecretBackend(device=True)
        # decrypt-verify EVERY path before timing anything
        host_slice = cts[: max(8, B // 32)]
        assert [key.decrypt(c) for c in host_slice] == ms[: len(host_slice)], \
            "per-op decrypt mismatch"
        assert key.decrypt_batch(cts) == ms, "batched host decrypt mismatch"
        assert key.decrypt_batch(cts, backend=dev, min_batch=1) == ms, \
            "Sanctum device decrypt mismatch"

        t_per_op = best_of(lambda: [key.decrypt(c) for c in host_slice],
                           repeats=args.repeats)
        per_op_ops = len(host_slice) / t_per_op

        t_host = best_of(lambda: key.decrypt_batch(cts),
                         repeats=args.repeats)
        host_ops = B / t_host

        # warm the device plan's compile outside the timed region (the
        # per-key jit compiles exactly once per batch shape)
        plan = plan_for(key, dev)
        plan.decrypt_batch(cts)
        t_dev = best_of(lambda: plan.decrypt_batch(cts),
                        repeats=args.repeats)
        dev_ops = B / t_dev

        rows.append(emit(
            _metric(bits),
            dev_ops,
            "ops/s",
            dev_ops / per_op_ops,
            bits=bits,
            batch=B,
            per_op_ops=round(per_op_ops, 1),
            batched_host_ops=round(host_ops, 1),
            sanctum_device_ops=round(dev_ops, 1),
            batched_host_speedup=round(host_ops / per_op_ops, 2),
            sanctum_speedup=round(dev_ops / per_op_ops, 2),
            verified=True,
        ))
        key.scrub()  # bench keys are synthetic, but model the hygiene
    return rows


if __name__ == "__main__":
    main()
