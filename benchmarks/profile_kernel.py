"""Microprofile of the Pallas CIOS building blocks (dev tool, not a config).

All timed functions return a scalar reduction of their output so only 4
bytes cross the (slow, tunneled) host<->device link per call while the full
computation still runs (a slice would let XLA dead-code-eliminate the rest).
"""

from __future__ import annotations

import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dds_tpu.bench_key import bench_paillier_key
from dds_tpu.ops import pallas_mont as pm
from dds_tpu.ops.montgomery import ModCtx


def timeit(fn, *args, repeats=5):
    np.asarray(fn(*args))  # warm/compile
    ts = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        np.asarray(fn(*args))
        ts.append(time.perf_counter() - t0)
    return min(ts)


def make_nofinal_mul(L, Lt, TB):
    """Same CIOS loop, but skip finalize: emit redundant t rows directly."""

    def kernel(n0_ref, a_ref, b_ref, nbx_ref, out_ref):
        n0 = n0_ref[0, 0]
        b = b_ref[:, :]
        nb = nbx_ref[0:L, :]
        t = pm._cios_loop(
            lambda i: a_ref[pl.ds(i, 1), :], b, nb, n0,
            jnp.zeros((Lt, TB), jnp.uint32), L,
        )
        out_ref[:, :] = t[0:L, :]

    def call(B):
        return pl.pallas_call(
            kernel,
            grid=(B // TB,),
            in_specs=[
                pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
                pl.BlockSpec((L, TB), lambda i: (0, i), memory_space=pltpu.VMEM),
                pl.BlockSpec((L, TB), lambda i: (0, i), memory_space=pltpu.VMEM),
                pl.BlockSpec((Lt, TB), lambda i: (0, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((L, TB), lambda i: (0, i), memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((L, B), jnp.uint32),
            interpret=pm._interpret_default(),
        )

    return call


def vpu_mul_rate() -> float:
    """Achieved VPU u32 multiply+mask rate (L-independent probe)."""
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.integers(0, 1 << 16, size=(512, 65536), dtype=np.uint32))

    @jax.jit
    def muls(x):
        y = x
        for _ in range(32):
            y = (y * x) & np.uint32(0xFFFF)
        return y.sum()

    return 32 * x.size / timeit(muls, x)           # u32 mul+mask / s


def roofline(L: int, vpu_rate: float):
    """Per-modmul roofline for the v2 kernel at limb count L (r4 verdict
    #4): from the achieved VPU u32-multiply rate and the MXU int8 MAC
    rate at this L's REDC shape, derive the floor time a v2 Montgomery
    multiply cannot beat.

    v2 cost model per modmul (base-2^16 digits, see ops/mont_mxu):
    - product: L^2 u32 multiplies on the VPU (each with mask/shift/add
      bookkeeping — the measured chain rate already includes one mask per
      multiply, so the bound charges L^2 / chain_rate);
    - REDC: two int8 band matmuls over L8=2L base-2^8 digits:
      L8^2 + 2*L8^2 = 3*(2L)^2 = 12 L^2 int8 MACs (x2 for the
      signed/mask split) on the MXU;
    - carry normalization: ~5 full-width Kogge-Stone passes, bandwidth-
      bound — not charged (the floor is compute-optimistic).
    """
    rng = np.random.default_rng(3)
    Mi = jnp.asarray(rng.integers(-128, 127, size=(4 * L, 2 * L), dtype=np.int8))
    Vi = jnp.asarray(rng.integers(-128, 127, size=(2 * L, 4096), dtype=np.int8))

    @jax.jit
    def mm(M, V):
        return jax.lax.dot(M, V, preferred_element_type=jnp.int32).sum()

    mxu_rate = (4 * L * 2 * L * 4096) / timeit(mm, Mi, Vi)  # int8 MAC/s

    floor_s = (L * L) / vpu_rate + (2 * 12 * L * L) / mxu_rate
    return mxu_rate, floor_s


def roofline_report(bits_list=(1024, 2048, 4096)):
    """Print the utilization table for BASELINE.md: moduli of `bits` (so
    L = bits/16 limbs in the direct-modulus case; Paillier folds run at
    2x that for n^2)."""
    from dds_tpu.ops import mont_mxu

    rng = np.random.default_rng(9)
    vpu_rate = vpu_mul_rate()  # L-independent: measure once
    for bits in bits_list:
        n = (1 << bits) - 159  # odd, full-width
        ctx = ModCtx.make(n)
        L = ctx.L
        mctx = mont_mxu.MxuCtx.make(ctx)
        B = 8192
        batch = jnp.asarray(
            rng.integers(0, 1 << 16, size=(B, L), dtype=np.uint32)
        )

        f = jax.jit(lambda x: mont_mxu.mul2_lm(mctx, x.T, x.T).sum())
        t = timeit(f, batch)
        mxu_rate, floor_s = roofline(L, vpu_rate)
        per = t / B
        print(
            f"L={L:4d} ({bits}-bit): v2 modmul {per*1e9:8.1f} ns | "
            f"compute floor {floor_s*1e9:8.1f} ns | utilization "
            f"{floor_s/per*100:5.1f}% | vpu {vpu_rate/1e12:.2f} T mul/s, "
            f"mxu {mxu_rate/1e12:.1f} T MAC/s"
        )


def main():
    key = bench_paillier_key()
    ctx = ModCtx.make(key.nsquare)
    L, TB = ctx.L, pm.MUL_TB
    Lt = pm._pad_rows(L)
    B = 8192
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.integers(0, 1 << 16, size=(L, B), dtype=np.uint32))
    b = jnp.asarray(rng.integers(0, 1 << 16, size=(L, B), dtype=np.uint32))

    f = jax.jit(lambda a, b: pm.mul_lm(ctx, a, b).sum())
    t_full = timeit(f, a, b)
    print(f"mul_lm       B={B}: {t_full*1e3:8.2f} ms  -> {t_full/B*1e9:7.1f} ns/modmul")

    nf = make_nofinal_mul(L, Lt, TB)(B)
    g = jax.jit(lambda a, b: nf(pm._n0(ctx), a, b, pm._nbx(ctx, TB)).sum())
    t_nf = timeit(g, a, b)
    print(f"no-finalize  B={B}: {t_nf*1e3:8.2f} ms  -> {t_nf/B*1e9:7.1f} ns/modmul")
    print(f"finalize share: {(t_full-t_nf)/t_full*100:.1f}%")

    # VPU elementwise throughput probes (32 chained ops on a 32M tile)
    x = jnp.asarray(rng.integers(0, 1 << 16, size=(512, 65536), dtype=np.uint32))

    @jax.jit
    def muls(x):
        y = x
        for _ in range(32):
            y = (y * x) & np.uint32(0xFFFF)
        return y.sum()

    t_m = timeit(muls, x)
    print(f"u32 mul+mask chain: {64 * x.size / t_m / 1e12:.2f} T elem-ops/s")

    @jax.jit
    def adds(x):
        y = x
        for _ in range(32):
            y = y + x
        return y.sum()

    t_a = timeit(adds, x)
    print(f"u32 add chain:      {32 * x.size / t_a / 1e12:.2f} T elem-ops/s")

    # MXU probes at the Montgomery-reduction shape (XLA level)
    L8 = 2 * L
    Bm = 4096
    Mi = jnp.asarray(rng.integers(-128, 127, size=(2 * L8, L8), dtype=np.int8))
    Vi = jnp.asarray(rng.integers(-128, 127, size=(L8, Bm), dtype=np.int8))

    @jax.jit
    def mm_i8(M, V):
        return jax.lax.dot(M, V, preferred_element_type=jnp.int32).sum()

    t_mm = timeit(mm_i8, Mi, Vi)
    macs = 2 * L8 * L8 * Bm
    print(f"int8 matmul ({2*L8}x{L8})@({L8}x{Bm}): {t_mm*1e3:.2f} ms  "
          f"{macs/t_mm/1e12:.1f} T MAC/s")

    Mf = jnp.asarray(rng.integers(0, 128, size=(2 * L8, L8)).astype(np.float32))
    Vf = jnp.asarray(rng.integers(0, 128, size=(L8, Bm)).astype(np.float32))

    @jax.jit
    def mm_f32(M, V):
        return jax.lax.dot(M, V, preferred_element_type=jnp.float32).sum()

    t_mf = timeit(mm_f32, Mf, Vf)
    print(f"f32 matmul  same shape: {t_mf*1e3:.2f} ms  {macs/t_mf/1e12:.1f} T MAC/s")

    print("\n-- v2 roofline (measured vs compute floor) --")
    roofline_report()


if __name__ == "__main__":
    main()
