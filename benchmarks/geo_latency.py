"""Geo latency: read-local quorum leases vs cross-region quorum reads.

The claim behind ISSUE 16 (Atlas): on a region-spanning replica group
under WAN latency, a plain ABD read pays two cross-region phases (read
+ write-back) per operation, so its p95 tracks the WAN round-trip; a
client holding a read-local quorum lease answers the same read in one
intra-region hop, because the lease pins every write quorum to include
the holder.  Safety survives revocation: when the lease is pulled out
from under the client mid-run, reads degrade to the full quorum round
(never to a stale answer) until a fresh lease is granted.

The harness drives ONE seeded write/read schedule twice against a fresh
3-region span constellation under an identical seeded `wan-*` ChaosNet
mesh each time:

- leased: client homed in r0 with leases on — reads take the single-hop
  fast path; halfway through, every group's r0 lease is revoked
  table-side, forcing refusals -> full-quorum fallbacks -> re-grant;
- quorum: leases off — every read is a full cross-region ABD round.

Every read is checked against the last acked write for its key (the
schedule is sequential, so any older value is a staleness violation);
`stale_reads` in the record counts violations across BOTH runs and must
be zero.

Reported record (`geo latency`, parsed by benchmarks/sentry.py
--check): value = quorum_p95 / local_p95 speedup, vs_baseline = the
same ratio, detail = both p95s (ms), read/lease/fallback censuses, the
WAN preset, and the revocation marker.

Usage: python -m benchmarks.geo_latency [--reads 96] [--keys 6]
       [--preset wan-100] [--scale 1.0] [--seed 31]
"""

from __future__ import annotations

import argparse
import asyncio
import random
import time

from benchmarks.common import emit


def _metric_sum(name: str, **match) -> float:
    """Sum a counter family over every label set matching `match`."""
    from dds_tpu.obs.metrics import metrics

    fam = metrics._families.get(name)
    if fam is None:
        return 0.0
    total = 0.0
    for key, v in fam.samples.items():
        labels = dict(key)
        if all(labels.get(k) == want for k, want in match.items()):
            total += v
    return total


def _schedule(args):
    """One seeded op schedule, identical for both variants: mostly reads
    over a small key set, with interleaved writes that move the freshness
    frontier the reads are checked against."""
    rng = random.Random(args.seed)
    keys = [f"GEO-{i}" for i in range(args.keys)]
    ops = []
    for i in range(args.reads):
        key = keys[rng.randrange(len(keys))]
        if rng.random() < args.p_write:
            ops.append(("w", key, f"{key}@{i}"))
        ops.append(("r", key, None))
    return keys, ops


def _p95_ms(latencies: list) -> float:
    ordered = sorted(latencies)
    return ordered[int(0.95 * (len(ordered) - 1))] * 1e3


async def _drive(args, keys, ops, leased: bool) -> dict:
    from dds_tpu.core.chaos import ChaosNet
    from dds_tpu.core.transport import InMemoryNet
    from dds_tpu.geo import wan
    from dds_tpu.shard import build_constellation

    regions = ["r0", "r1", "r2"]
    net = ChaosNet(InMemoryNet(), seed=args.seed + 7)
    wan.apply_profiles(net, wan.mesh(regions, args.preset),
                       scale=args.scale)
    const = build_constellation(
        net, shard_count=2, vnodes_per_group=8, seed=args.seed,
        n_active=3, n_sentinent=0, quorum=2,
        regions=regions, placement="span",
        lease_ttl=(args.lease_ttl if leased else 0.0),
        client_region=("r0" if leased else ""),
    )
    r = const.router
    served0 = _metric_sum("dds_geo_local_reads_total", result="served")
    fell0 = _metric_sum("dds_geo_local_read_fallbacks_total")

    last: dict[str, str] = {}
    for k in keys:
        await r.write_set(k, [f"{k}@preload"])
        last[k] = f"{k}@preload"

    lat, stale, reads_done = [], 0, 0
    revoke_at = args.reads // 2
    try:
        for op, key, value in ops:
            if op == "w":
                await r.write_set(key, [value])
                last[key] = value
                continue
            if leased and reads_done == revoke_at:
                # the mid-run rug-pull: every group's table drops the r0
                # lease, so the client's next token is refused and reads
                # degrade to the full quorum until a fresh grant lands
                for g in const.groups:
                    if g.lease_table is not None:
                        g.lease_table.revoke("r0")
            t0 = time.perf_counter()
            got = await r.fetch_set(key)
            lat.append(time.perf_counter() - t0)
            reads_done += 1
            if got != [last[key]]:
                stale += 1
    finally:
        await const.stop()
        await net.quiesce()

    return {
        "p95_ms": _p95_ms(lat),
        "reads": reads_done,
        "stale": stale,
        "leased_reads": int(
            _metric_sum("dds_geo_local_reads_total", result="served")
            - served0),
        "fallbacks": int(
            _metric_sum("dds_geo_local_read_fallbacks_total") - fell0),
    }


def main(argv=None) -> list:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--reads", type=int, default=96,
                    help="reads per variant (writes ride on top)")
    ap.add_argument("--keys", type=int, default=6,
                    help="distinct keys in the schedule")
    ap.add_argument("--p-write", type=float, default=0.15,
                    help="probability a read is preceded by a fresh write")
    ap.add_argument("--preset", default="wan-100",
                    choices=["wan-100", "wan-200", "wan-300"],
                    help="WAN RTT preset for every cross-region pair")
    ap.add_argument("--scale", type=float, default=1.0,
                    help="multiplier on WAN delays (CI-friendly shrink)")
    ap.add_argument("--lease-ttl", type=float, default=2.0,
                    help="read-local lease TTL for the leased variant")
    ap.add_argument("--seed", type=int, default=31)
    args = ap.parse_args(argv)

    keys, ops = _schedule(args)
    local = asyncio.run(_drive(args, keys, ops, leased=True))
    quorum = asyncio.run(_drive(args, keys, ops, leased=False))

    ratio = quorum["p95_ms"] / max(local["p95_ms"], 1e-9)
    row = emit(
        "geo latency",
        ratio,
        "x",
        ratio,
        local_p95_ms=round(local["p95_ms"], 3),
        quorum_p95_ms=round(quorum["p95_ms"], 3),
        reads=local["reads"] + quorum["reads"],
        leased_reads=local["leased_reads"],
        fallbacks=local["fallbacks"],
        revoked_mid_run=True,
        stale_reads=local["stale"] + quorum["stale"],
        wan_preset=args.preset,
        wan_scale=args.scale,
        keys=args.keys,
        lease_ttl_s=args.lease_ttl,
        seed=args.seed,
    )
    return [row]


if __name__ == "__main__":
    main()
