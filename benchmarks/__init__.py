"""BASELINE.md benchmark suite (configs 2-5).

`bench.py` at the repo root is config #1 (the north-star encrypted SUM);
this package holds the remaining BASELINE.json configs:

- sweep.py    (#2) Paillier key-size sweep 2048/3072/4096: batched SUM + scalar-MUL
- product.py  (#3) multiplicative-HE (RSA) PRODUCT aggregate
- bft_sum.py  (#4) 4-replica BFT f=1 end-to-end encrypted SUM through the proxy
- mixed.py    (#5) OPE range + Paillier SUM mixed YCSB-style workload

Run all:  python -m benchmarks.run_all
Each module emits one JSON line per measurement (same shape as bench.py).
"""
