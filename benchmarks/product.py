"""BASELINE config #3: multiplicative-HE PRODUCT aggregate.

The proxy's `MultAll` route folds RSA-multiplicative ciphertexts with
`HomoMult.multiply` (`dds/http/DDSRestServer.scala:505-524`): a modmul
fold mod n. Times that fold cpu vs tpu (one fused Montgomery tree
reduction over device-resident limbs), decrypt-verified first.

The reference ships an RSA-1024 multiplicative key (`client.conf:86`);
we sweep 1024 and 2048.

Usage: python -m benchmarks.product [--k 16384] [--sizes 1024,2048]
"""

from __future__ import annotations

import argparse
import secrets


from benchmarks.common import best_of, emit, sustained_device


def product_one(bits: int, K: int, repeats: int = 3) -> dict:
    import jax

    from dds_tpu.models.backend import CpuBackend, TpuBackend
    from dds_tpu.models.mult import RsaMultKey
    from dds_tpu.ops import bignum as bn
    from dds_tpu.ops.montgomery import ModCtx

    key = RsaMultKey.generate(bits)
    pk = key.public
    # min_device_batch=0: the correctness gate must exercise the device fold
    cpu, tpu = CpuBackend(), TpuBackend(min_device_batch=0)

    # correctness gate: PRODUCT of real ciphertexts decrypts to the product
    vals = [secrets.randbelow(1 << 16) + 1 for _ in range(8)]
    cts = [pk.encrypt(v) for v in vals]
    want = 1
    for v in vals:
        want = want * v % pk.n
    assert key.decrypt(tpu.modmul_fold(cts, pk.n)) == want

    cs = [secrets.randbelow(pk.n) for _ in range(K)]
    cpu_s = best_of(lambda: cpu.modmul_fold(cs, pk.n), repeats)
    cpu_ops = (K - 1) / cpu_s

    ctx = ModCtx.make(pk.n)
    resident = jax.device_put(bn.ints_to_batch(cs, ctx.L))
    jax.block_until_ready(resident)
    tpu_s = sustained_device(
        lambda: tpu.reduce_mul_device(ctx, resident), repeats=repeats
    )
    tpu_ops = (K - 1) / tpu_s
    return emit(
        f"encrypted PRODUCT ops/sec @ RSA-{bits} (MultAll fold)",
        tpu_ops,
        "ops/s",
        tpu_ops / cpu_ops,
        K=K,
        limbs=ctx.L,
        cpu_ops_per_sec=round(cpu_ops, 1),
        tpu_fold_ms=round(tpu_s * 1e3, 2),
        cpu_fold_ms=round(cpu_s * 1e3, 2),
    )


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--k", type=int, default=16384)
    ap.add_argument("--sizes", default="1024,2048")
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args(argv)
    return [
        product_one(int(s), args.k, args.repeats) for s in args.sizes.split(",")
    ]


if __name__ == "__main__":
    main()
