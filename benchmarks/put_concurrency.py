"""Concurrent-client PutSet throughput: where does write time go?

The reference drives N concurrent client actors against the proxy
(`Main.scala:166-170`); this benchmark reproduces that shape — N
`DDSHttpClient`s (each with real client-side HE on the canonical
8-column schema) executing PutSet-only digests against one launched
deployment (4-replica BFT f=1, quorum 3, like BASELINE config #4) — and
answers r4 verdict #7: is the ~1k ops/s PutSet figure protocol-bound or
Python-bound?

Per N it reports aggregate PutSet ops/s plus the server-side tracer
spans for the write path (http.POST.PutSet wall, abd.write quorum time)
and the client-side encrypt share, so the dominant cost is named, not
guessed.

Usage: python -m benchmarks.put_concurrency [--ops 256] [--clients 1 4 16]
"""

from __future__ import annotations

import argparse
import asyncio
import time

from benchmarks.common import emit

METRIC = "concurrent-client PutSet ops/sec @ 4-replica BFT f=1"


def make_digest(n_ops: int, seed: int):
    import random

    from dds_tpu.clt import instructions as I

    rng = random.Random(seed)
    rows = [
        [rng.randrange(1 << 16), f"name-{i}", rng.randrange(1 << 24),
         rng.randrange(1, 1 << 16), "a", "b", "c", f"blob-{i}-{seed}"]
        for i in range(n_ops)
    ]
    return I.Digest([I.PutSet(r) for r in rows])


async def run_one(n_clients: int, ops_per_client: int, bulk: str = "") -> dict:
    import random

    from dds_tpu.clt.client import ClientConfig, DDSHttpClient
    from dds_tpu.run import launch, load_provider
    from dds_tpu.utils.config import DDSConfig
    from dds_tpu.utils.trace import tracer

    cfg = DDSConfig()
    cfg.replicas.endpoints = [f"replica-{i}" for i in range(4)]
    cfg.replicas.sentinent = []
    cfg.replicas.byz_quorum_size = 3
    cfg.replicas.byz_max_faults = 1
    cfg.recovery.enabled = False
    cfg.proxy.port = 0
    cfg.client.paillier_bits = 2048
    cfg.client.rsa_bits = 1024
    cfg.client.bulk_encrypt_backend = bulk

    provider = load_provider(cfg)
    dep = await launch(cfg)
    try:
        clients = [
            DDSHttpClient(
                provider,
                ClientConfig(proxies=[f"127.0.0.1:{dep.server.cfg.port}"]),
                rng=random.Random(1000 + i),
            )
            for i in range(n_clients)
        ]
        digests = [make_digest(ops_per_client, seed=i) for i in range(n_clients)]

        # client-side encrypt share: encrypt one digest untimed by the
        # server to know the per-row HE cost in isolation
        t0 = time.perf_counter()
        for instr in digests[0].payload[: min(32, ops_per_client)]:
            provider.encrypt_row(instr.set, 8, clients[0].cfg.schema)
        enc_row_ms = (time.perf_counter() - t0) / min(32, ops_per_client) * 1e3

        tracer.reset()
        t0 = time.perf_counter()
        reports = await asyncio.gather(
            *(c.execute(d) for c, d in zip(clients, digests))
        )
        wall = time.perf_counter() - t0
        total_ops = sum(r.operations for r in reports)
        failed = sum(r.failed for r in reports)
        assert failed == 0, f"{failed} PutSets failed"

        spans = {
            name: {k: round(v, 3) for k, v in s.items() if k in ("mean_ms", "count")}
            for name, s in tracer.summary().items()
            if name in ("http.POST.PutSet", "abd.write", "abd.read_tags")
        }
        return {
            "clients": n_clients,
            "ops_per_sec": total_ops / wall,
            "wall_s": wall,
            "enc_row_ms": enc_row_ms,
            "spans": spans,
        }
    finally:
        await dep.stop()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", type=int, default=256, help="PutSets per client")
    ap.add_argument("--clients", type=int, nargs="+", default=[1, 4, 16])
    ap.add_argument("--bulk", default="", help="client bulk-encrypt backend"
                    " (tpu | native; empty = per-op DJN host path)")
    args = ap.parse_args(argv)

    results = [asyncio.run(run_one(n, args.ops, args.bulk)) for n in args.clients]
    base = results[0]["ops_per_sec"]
    best = max(results, key=lambda r: r["ops_per_sec"])
    rows = []
    for r in results:
        rows.append(
            emit(
                METRIC,
                r["ops_per_sec"],
                "ops/s",
                r["ops_per_sec"] / base,  # scaling vs 1 client
                clients=r["clients"],
                ops_per_client=args.ops,
                enc_row_ms=round(r["enc_row_ms"], 3),
                putset_server_mean_ms=r["spans"].get("http.POST.PutSet", {}).get("mean_ms"),
                abd_write_mean_ms=r["spans"].get("abd.write", {}).get("mean_ms"),
            )
        )
    return rows


if __name__ == "__main__":
    main()
