"""Shared timing helpers for the benchmark suite."""

from __future__ import annotations

import json
import time


def best_of(fn, repeats: int = 3) -> float:
    """Min wall-clock seconds over `repeats` timed calls. All calls are
    timed — callers must warm/compile with an explicit untimed call first."""
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return min(times)


def sustained_device(dispatch, R: int = 16, repeats: int = 3) -> float:
    """Sustained per-dispatch seconds for a device computation.

    `dispatch()` must enqueue work and return a jax array WITHOUT fetching.
    Pipelines R dispatches on the device stream and fetches ONE device-side
    scalar combine, so the host<->device round-trip (~tens of ms on
    tunneled platforms) is paid once per R dispatches — matching how a
    serving proxy overlaps aggregate dispatches. A blocking fetch per
    dispatch would time the link latency, not the kernels.
    """
    import jax
    import numpy as np

    combine = jax.jit(lambda xs: sum(x.sum() for x in xs))

    def run():
        return np.asarray(combine([dispatch() for _ in range(R)]))

    run()  # warm/compile
    ts = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        run()
        ts.append(time.perf_counter() - t0)
    return min(ts) / R


def emit(metric: str, value: float, unit: str, vs_baseline: float, **detail) -> dict:
    row = {
        "metric": metric,
        "value": round(value, 1),
        "unit": unit,
        "vs_baseline": round(vs_baseline, 3),
    }
    if detail:
        row["detail"] = detail
    print(json.dumps(row))
    return row
