"""Shared timing helpers for the benchmark suite."""

from __future__ import annotations

import json
import time

# previous kprof snapshot, so each emitted record carries only ITS OWN
# kernel work (delta), not the whole run's cumulative totals
_kprof_prev: dict | None = None


def _kernel_delta() -> dict | None:
    """Kernel accounting since the last emit(): dispatch (trace+compile)
    vs execute ms and compile-cache hit rates from obs.kprof. None when no
    kernel ran in the window — pure-protocol benchmarks stay clean."""
    global _kprof_prev
    from dds_tpu.obs import kprof

    cur = kprof.kernel_summary()
    prev, _kprof_prev = _kprof_prev, cur
    # clamp at 0: span-ring eviction can shrink the cumulative totals the
    # summary is computed from on very long runs
    d = {
        "dispatch_ms": round(
            max(0.0, cur["dispatch_ms"] - (prev["dispatch_ms"] if prev else 0.0)), 3
        ),
        "execute_ms": round(
            max(0.0, cur["execute_ms"] - (prev["execute_ms"] if prev else 0.0)), 3
        ),
    }
    caches = {}
    for name, c in cur["compile_cache"].items():
        p = (prev or {}).get("compile_cache", {}).get(name, {})
        hits = max(0, c["hits"] - p.get("hits", 0))
        misses = max(0, c["misses"] - p.get("misses", 0))
        if hits or misses:
            caches[name] = {
                "hits": hits,
                "misses": misses,
                "hit_rate": round(hits / (hits + misses), 4),
            }
    if caches:
        d["compile_cache"] = caches
    if d["dispatch_ms"] or d["execute_ms"] or caches:
        return d
    return None


def best_of(fn, repeats: int = 3) -> float:
    """Min wall-clock seconds over `repeats` timed calls. All calls are
    timed — callers must warm/compile with an explicit untimed call first."""
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return min(times)


def sustained_device(dispatch, R: int = 16, repeats: int = 3) -> float:
    """Sustained per-dispatch seconds for a device computation.

    `dispatch()` must enqueue work and return a jax array WITHOUT fetching.
    Pipelines R dispatches on the device stream and fetches ONE device-side
    scalar combine, so the host<->device round-trip (~tens of ms on
    tunneled platforms) is paid once per R dispatches — matching how a
    serving proxy overlaps aggregate dispatches. A blocking fetch per
    dispatch would time the link latency, not the kernels.
    """
    import jax
    import numpy as np

    combine = jax.jit(lambda xs: sum(x.sum() for x in xs))

    def run():
        return np.asarray(combine([dispatch() for _ in range(R)]))

    run()  # warm/compile
    ts = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        run()
        ts.append(time.perf_counter() - t0)
    return min(ts) / R


def emit(metric: str, value: float, unit: str, vs_baseline: float, **detail) -> dict:
    row = {
        "metric": metric,
        "value": round(value, 1),
        "unit": unit,
        "vs_baseline": round(vs_baseline, 3),
    }
    if detail:
        row["detail"] = detail
    try:
        kernel = _kernel_delta()
    except Exception:
        kernel = None  # telemetry must never fail a benchmark
    if kernel is not None:
        row["kernel"] = kernel
    try:
        # perf-regression sentry feed: persist per-kernel p50/p95
        # dispatch/execute stats into the baseline file (new kernels only
        # unless DDS_KERNEL_BASELINE_UPDATE; DDS_KERNEL_BASELINE="" turns
        # it off). benchmarks/sentry.py compares later runs against it.
        from dds_tpu.obs import sentry as _sentry

        _sentry.persist_from_tracer()
    except Exception:
        pass  # the baseline is telemetry too — never fail a benchmark
    print(json.dumps(row))
    return row
