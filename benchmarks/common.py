"""Shared timing helpers for the benchmark suite."""

from __future__ import annotations

import json
import time


def best_of(fn, repeats: int = 3) -> float:
    """Min wall-clock seconds over `repeats` timed calls. All calls are
    timed — callers must warm/compile with an explicit untimed call first."""
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return min(times)


def emit(metric: str, value: float, unit: str, vs_baseline: float, **detail) -> dict:
    row = {
        "metric": metric,
        "value": round(value, 1),
        "unit": unit,
        "vs_baseline": round(vs_baseline, 3),
    }
    if detail:
        row["detail"] = detail
    print(json.dumps(row))
    return row
