"""Heliograph canary-plane cost + the silent-corruption drill.

    python -m benchmarks.canary_overhead [--rate 120] [--duration 2]

Two records, both through the full in-process stack (default 9-replica/
quorum-5 ABD topology behind one REST proxy):

- `canary overhead` — a cadence sweep: the open-loop, coordinated-
  omission-safe load plane (fabric/loadgen) drives the same mixed
  GetSet/WriteElement/SumAll workload once with Heliograph OFF
  (baseline) and once per probe cadence. The number the record exists
  for is `overhead_pct` at the DEFAULT 5 s cadence: an active canary
  plane is supposed to cost <= 1% goodput — five golden transactions
  every few seconds against a proxy serving hundreds of requests per
  second is noise, and this record is where CI watches that stay true.
  The sweep's shorter cadences show where the cost curve actually
  starts (the rate-bounded carve-out caps the worst case).

- `canary drill` — the seeded silent-corruption fault: one stored
  Paillier ciphertext of the canary population is mutated IN PLACE on
  every replica, PAST the transport-HMAC boundary (each replica re-MACs
  its corrupted answer, quorums agree, `GET /GetSet` keeps serving 200
  — every passive surface stays green). The record proves the tentpole
  claim: decrypt-and-verify flags `wrong_answer` within a bounded
  number of probe periods, raises a Watchtower incident, and the
  exemplar trace id in `GET /canary` matches the incident's.

Both records land via `benchmarks.common.emit`; `sentry.py --check`
validates their shape (exit 2 on malformed).
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_CADENCE = 5.0


async def _launch(cadence: float | None, *, population: int = 4,
                  audit: bool = False):
    from dds_tpu.run import launch
    from dds_tpu.utils.config import DDSConfig

    cfg = DDSConfig()
    cfg.proxy.port = 0
    cfg.recovery.enabled = False    # keep timing clean of proactive restarts
    cfg.obs.audit_enabled = audit
    if cadence is not None:
        cfg.heliograph.enabled = True
        cfg.heliograph.cadence = cadence
        cfg.heliograph.jitter = 0.25
        cfg.heliograph.population = population
    return await launch(cfg)


async def _measure(cadence: float | None, rate: float, duration: float,
                   keys: int, seed: int) -> dict:
    """One load point: goodput under the mixed open-loop workload with
    the prober off (cadence None) or on at `cadence`."""
    from dds_tpu.fabric.loadgen import OpenLoopLoad

    dep = await _launch(cadence)
    try:
        load = OpenLoopLoad([f"127.0.0.1:{dep.server.cfg.port}"],
                            keys=keys, seed=seed)
        await load.seed()
        report = await load.run(rate, duration)
        probes, probe_ok = 0, 0
        if dep.server.heliograph is not None:
            led = dep.server.heliograph.ledger.report()
            probes = led["probes_recorded"]
            probe_ok = sum(n for k, n in led["counts"].items()
                           if k.endswith(".ok") or k.endswith(".slow"))
        return {
            "cadence": cadence,
            "good": report.good,
            "goodput_rps": round(report.achieved_rps, 2),
            "p95_ms": round(report.p95_ms, 3),
            "probes": probes,
            "probes_ok": probe_ok,
        }
    finally:
        await dep.stop()


async def _drill(cadence: float, settle: float) -> dict:
    """Seed valid-HMAC ciphertext corruption and time its detection."""
    import json as _json

    from dds_tpu.http.miniserver import http_request
    from dds_tpu.obs.heliograph import seed_ciphertext_corruption
    from dds_tpu.obs.watchtower import watchtower

    dep = await _launch(cadence, audit=True)
    try:
        h = dep.server.heliograph
        port = dep.server.cfg.port

        async def _sum_state() -> dict:
            return h.ledger.report()["kinds"].get("sum", {})

        # wait for the prober to come up green (keygen + populate + the
        # first full probe cycle)
        deadline = time.monotonic() + settle
        while time.monotonic() < deadline:
            if (await _sum_state()).get("verdict") == "ok":
                break
            await asyncio.sleep(0.05)
        else:
            raise RuntimeError("prober never reached a green sum probe")

        cycles_before = h.cycles
        mutated = seed_ciphertext_corruption(
            dep.replicas, h.client.keys[0], position=2)
        if mutated == 0:
            raise RuntimeError("seeded fault mutated no replica")

        # the passive surface stays green: the quorum read keeps serving
        # 200 over the (valid-MAC, wrong) ciphertext
        status, _ = await http_request(
            "127.0.0.1", port, "GET", f"/GetSet/{h.client.keys[0]}",
            timeout=5.0)
        passive_green = status == 200

        # ... and decrypt-and-verify catches it within bounded periods
        deadline = time.monotonic() + settle
        while time.monotonic() < deadline:
            state = await _sum_state()
            if state.get("last_failure", {}).get("verdict") == "wrong_answer":
                break
            await asyncio.sleep(0.02)
        else:
            raise RuntimeError("corruption was never detected")
        periods = max(1, h.cycles - cycles_before + 1)

        trace = state["last_failure"]["trace_id"]
        incidents = [v for v in watchtower.verdicts()
                     if v.invariant == "canary_wrong_answer"]
        # the exemplar must resolve end to end: the /canary report's
        # trace id IS the Watchtower incident's
        status, body = await http_request(
            "127.0.0.1", port, "GET", "/canary", timeout=5.0)
        served = _json.loads(body.decode()) if status == 200 else {}
        served_trace = served.get("kinds", {}).get("sum", {}).get(
            "last_failure", {}).get("trace_id")
        return {
            "replicas_mutated": mutated,
            "detected_within_periods": periods,
            "passive_green": passive_green,
            "verdict": "wrong_answer",
            "trace_id": trace,
            "watchtower_incidents": len(incidents),
            "incident_trace_match": bool(
                incidents and any(v.trace_id == trace for v in incidents)),
            "exemplar_resolved": served_trace == trace,
        }
    finally:
        await dep.stop()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rate", type=float, default=120.0,
                    help="open-loop arrival rate (req/s)")
    ap.add_argument("--duration", type=float, default=2.0)
    ap.add_argument("--keys", type=int, default=48)
    ap.add_argument("--cadences", default="5.0,1.0,0.25",
                    help="probe cadences (s) swept against the baseline")
    ap.add_argument("--drill-cadence", type=float, default=0.25)
    ap.add_argument("--settle", type=float, default=20.0,
                    help="drill wait budget for keygen/populate/detection")
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args(argv)

    from benchmarks.common import emit

    cadences = [float(c) for c in args.cadences.split(",") if c.strip()]
    if DEFAULT_CADENCE not in cadences:
        cadences.insert(0, DEFAULT_CADENCE)

    off = asyncio.run(_measure(None, args.rate, args.duration,
                               args.keys, args.seed))
    points = {}
    for cadence in cadences:
        on = asyncio.run(_measure(cadence, args.rate, args.duration,
                                  args.keys, args.seed))
        points[str(cadence)] = {
            "goodput_rps": on["goodput_rps"],
            "p95_ms": on["p95_ms"],
            "probes": on["probes"],
            "probes_ok": on["probes_ok"],
            "overhead_pct": round(
                (1.0 - on["good"] / max(1, off["good"])) * 100.0, 2),
        }
    at_default = points[str(DEFAULT_CADENCE)]

    rows = [emit(
        "canary overhead",
        at_default["goodput_rps"],
        "req/s",
        at_default["goodput_rps"] / max(1e-9, off["goodput_rps"]),
        rate=args.rate,
        duration=args.duration,
        open_loop=True,
        default_cadence_s=DEFAULT_CADENCE,
        overhead_pct=at_default["overhead_pct"],
        baseline_goodput_rps=off["goodput_rps"],
        baseline_p95_ms=off["p95_ms"],
        cadences=points,
    )]

    drill = asyncio.run(_drill(args.drill_cadence, args.settle))
    rows.append(emit(
        "canary drill",
        drill["detected_within_periods"],
        "probe-periods",
        1.0,
        drill_cadence_s=args.drill_cadence,
        **drill,
    ))
    return rows


if __name__ == "__main__":
    main()
