"""Encrypt-grade modexp: FULL-WIDTH (2048-bit) exponent batch benchmark.

The north star names "the modular exponentiations behind encrypt,
decrypt"; the reference's client pays one n-bit-exponent modexp per
encrypted value (`utils/SJHomoLibProvider.scala:74-86`). r4 verdict #3:
no TPU number existed for a 2048-bit-exponent batch modexp — the op that
dominates encrypt/decrypt. This measures r^n mod n^2 (Paillier-2048
obfuscator generation, exponent = n = 2048 bits, modulus = n^2 = 4096
bits, L=256) at batch B for:

- v2:      MXU band-REDC ladder (mont_mxu.pow_mod2) — sustained + single
           dispatch;
- v1:      fused CIOS Pallas ladder (pallas_mont.pow_mod);
- native:  host C++ CIOS (dds_tpu.native.powmod_batch);
- python:  CPython pow() loop (the CPU baseline);
- DJN:     the 448-bit short-exponent host path (what per-op encryption
           uses today) — the honest host contender for bulk encryption.

Also measures batched CRT DECRYPT (PaillierKey.decrypt_batch on the
Sanctum device plane: both half-width CRT legs fused into one dispatch,
secret moduli kept out of the shared caches) vs the per-op host decrypt,
decrypt-verified. benchmarks/decrypt_throughput.py is the dedicated
per-key-size decrypt sweep.

vs_baseline = v2 sustained vs python pow.

Usage: python -m benchmarks.encrypt_modexp [--b 256] [--repeats 3]
"""

from __future__ import annotations

import argparse
import time

import numpy as np

from benchmarks.common import best_of, emit, sustained_device

METRIC = "encrypt-grade modexp ops/sec @ 2048-bit exponent, Paillier-2048 (r^n mod n^2)"


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--b", type=int, default=256)
    ap.add_argument("--pipelined", type=int, default=4)
    args = ap.parse_args(argv)
    B = args.b

    import jax

    from dds_tpu import native
    from dds_tpu.bench_key import bench_paillier_key
    from dds_tpu.ops import bignum as bn
    from dds_tpu.ops import mont_mxu, pallas_mont
    from dds_tpu.ops.montgomery import ModCtx

    key = bench_paillier_key()
    pk = key.public
    n, n2 = pk.n, pk.nsquare
    ctx = ModCtx.make(n2)
    mctx = mont_mxu.MxuCtx.make(ctx)
    rng = np.random.default_rng(11)

    rs = [int.from_bytes(rng.bytes(ctx.L), "little") % n2 for _ in range(B)]
    batch = bn.ints_to_batch(rs, ctx.L)
    dev = jax.device_put(batch)

    # correctness first: v2 against python pow on a slice
    want = [pow(r, n, n2) for r in rs[:4]]
    got = bn.batch_to_ints(np.asarray(mont_mxu.pow_mod2(mctx, batch[:4], n)))
    assert got == want, "v2 full-width modexp mismatch"

    # python pow baseline (per-op host loop)
    t_py = best_of(lambda: [pow(r, n, n2) for r in rs[: max(8, B // 32)]], repeats=2)
    py_ops = max(8, B // 32) / t_py

    # DJN short-exponent host path (the current per-op encrypt cost)
    t_djn = best_of(lambda: [pk.blind_fast() for _ in range(32)], repeats=2)
    djn_ops = 32 / t_djn

    # native host C++ batch
    t_nat = best_of(lambda: native.powmod_batch(rs[: max(8, B // 32)], n, n2), repeats=2)
    nat_ops = max(8, B // 32) / t_nat

    # v2 / v1 device ladders
    v2_sus = sustained_device(lambda: mont_mxu.pow_mod2(mctx, dev, n), R=args.pipelined)

    def v2_block():
        return np.asarray(mont_mxu.pow_mod2(mctx, dev, n))

    v2_block()
    v2_lat = best_of(v2_block, repeats=2)

    v1_sus = sustained_device(lambda: pallas_mont.pow_mod(ctx, dev, n), R=args.pipelined)

    # batched CRT decrypt: Sanctum device path (both half-width legs
    # fused into one dispatch, secret moduli never in the shared caches
    # — benchmarks/decrypt_throughput.py is the dedicated sweep) vs
    # per-op host decrypt, verified
    from dds_tpu.sanctum import SecretBackend, plan_for

    sb = SecretBackend(device=True)
    ms_plain = [int(x) for x in rng.integers(0, 1 << 48, size=B)]
    blinds = [pk.blind() for _ in range(32)]
    cts = [pk.encrypt(m, rn=blinds[i % 32]) for i, m in enumerate(ms_plain)]
    got = key.decrypt_batch(cts, backend=sb, min_batch=1)
    assert got == ms_plain, "batched CRT decrypt mismatch"
    dec_plan = plan_for(key, sb)  # warm plan; timing excludes its compile
    dec_dev = best_of(lambda: dec_plan.decrypt_batch(cts), repeats=2)
    host_slice = cts[: max(8, B // 32)]
    dec_host = best_of(lambda: [key.decrypt(c) for c in host_slice], repeats=2)
    dec_dev_ops = B / dec_dev
    dec_host_ops = len(host_slice) / dec_host

    row = emit(
        METRIC,
        B / v2_sus,
        "ops/s",
        (B / v2_sus) / py_ops,
        B=B,
        exp_bits=n.bit_length(),
        v2_sustained_ops=round(B / v2_sus, 1),
        v2_single_dispatch_ops=round(B / v2_lat, 1),
        v1_sustained_ops=round(B / v1_sus, 1),
        native_host_ops=round(nat_ops, 1),
        python_pow_ops=round(py_ops, 1),
        djn_short_exp_host_ops=round(djn_ops, 1),
        v2_ms_per_batch=round(v2_sus * 1e3, 1),
        decrypt_batch_device_ops=round(dec_dev_ops, 1),
        decrypt_host_ops=round(dec_host_ops, 1),
        decrypt_speedup=round(dec_dev_ops / dec_host_ops, 2),
    )
    return [row]


if __name__ == "__main__":
    main()
