"""Spyglass search-latency benchmark: indexed routes vs the legacy scan.

The structural claim of ISSUE 13: a warm `Search*`/`Order*`/`Range`
query should cost ONE batched tag-validation quorum round plus one
predicate kernel dispatch (ops/predicate over the SearchPlane's packed
columns), not a full keyspace materialization. The legacy scan — the
reference's `DDSRestServer.scala:397-446` shape, which re-reads every
stored set quorum-deep per query — pays O(N) ABD value rounds before
its host filter loop even starts.

The harness launches the SAME store twice and drives identical query
streams end-to-end through the REST edge:

- legacy  — search disabled AND the tag-validated aggregate cache
  disabled: every query re-fetches the whole keyspace through full ABD
  reads, exactly the reference's cache-less scan (the path Spyglass
  replaces);
- indexed — `[search] enabled` (cache on): warm queries validate the
  index with one `read_tags` round and answer from the packed columns.

Both deployments are seeded with the same value rows (distinct ints at
position 0, a DET-style label at position 1), and every op's keysets
are mapped back to row ids and checked EQUAL across deployments before
any timing (the equality gate). One `search latency` record per op
lands in results.json via benchmarks/common.emit() (value = indexed
queries/s, vs_baseline = legacy_ms / indexed_ms, >1 = indexed wins).
benchmarks/sentry.py --check validates the records.

Usage: python -m benchmarks.search_latency [--keys 96] [--repeats 3]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random
import time

from benchmarks.common import emit


def _config(args, indexed: bool):
    from dds_tpu.utils.config import DDSConfig

    cfg = DDSConfig()
    cfg.replicas.endpoints = [f"replica-{i}" for i in range(4)]
    cfg.replicas.sentinent = []
    cfg.replicas.byz_quorum_size = 3
    cfg.replicas.byz_max_faults = 1
    cfg.proxy.port = 0
    # quiet fabric: the bench measures query paths, not recovery timers
    cfg.recovery.enabled = False
    cfg.obs.audit_enabled = False
    cfg.search.enabled = indexed
    return cfg


async def _seed(host: str, port: int, rows: list[list]) -> dict[str, int]:
    from dds_tpu.http.miniserver import http_request

    key_to_row: dict[str, int] = {}
    for i, row in enumerate(rows):
        status, body = await http_request(
            host, port, "POST", "/PutSet",
            json.dumps({"contents": row}).encode(), timeout=10.0,
        )
        if status != 200:
            raise RuntimeError(f"store seeding failed with {status}")
        key_to_row[body.decode()] = i
    return key_to_row


async def _drive(args) -> list[dict]:
    from dds_tpu.http.miniserver import http_request
    from dds_tpu.run import launch

    rng = random.Random(args.seed)
    # distinct position-0 ints: cross-deployment keysets compare by row
    # id without tie-order ambiguity (keys are server-assigned)
    vals = rng.sample(range(1, 1 << 40), args.keys)
    rows = [[v, f"city{i % 7}"] for i, v in enumerate(vals)]
    thr = sorted(vals)[args.keys // 2]
    lo_b, hi_b = sorted(vals)[args.keys // 4], sorted(vals)[3 * args.keys // 4]

    cases = [
        ("gt", "POST", "/SearchGt?position=0", {"value": thr}),
        ("eq", "POST", "/SearchEq?position=1", {"value": "city3"}),
        ("order", "GET", "/OrderLS?position=0", None),
        ("range", "POST", "/Range?position=0",
         {"value1": lo_b, "value2": hi_b}),
    ]

    async def run_variant(indexed: bool) -> dict:
        dep = await launch(_config(args, indexed))
        if not indexed:
            # legacy = the reference's cache-less scan: full keyspace ABD
            # value reads per query (the cost Spyglass's one-round
            # validation replaces). The tag-validated aggregate cache is
            # a later addition the reference never had — off, so the
            # baseline is the true `DDSRestServer.scala` shape.
            dep.server.cfg.aggregate_cache = False
        host, port = "127.0.0.1", dep.server.cfg.port
        key_to_row = await _seed(host, port, rows)

        async def query(method, target, obj) -> list[int]:
            body = json.dumps(obj).encode() if obj is not None else None
            status, out = await http_request(
                host, port, method, target, body, timeout=30.0,
            )
            if status != 200:
                raise RuntimeError(f"{target} answered {status}")
            return [key_to_row[k] for k in json.loads(out)["keyset"]]

        results: dict[str, list[int]] = {}
        timings: dict[str, float] = {}
        for op, method, target, obj in cases:
            # warm pass: pack build + kernel compile (indexed) / cache
            # symmetry (legacy); its result is the equality-gate operand
            results[op] = await query(method, target, obj)
            best = []
            for _ in range(args.repeats):
                t0 = time.perf_counter()
                got = await query(method, target, obj)
                best.append(time.perf_counter() - t0)
                assert got == results[op], f"{op} answered unstably"
            timings[op] = min(best) * 1e3
        await dep.stop()
        return {"results": results, "timings": timings}

    legacy = await run_variant(indexed=False)
    indexed = await run_variant(indexed=True)

    out = []
    for op, _, _, _ in cases:
        # equality gate: the indexed route must select exactly the rows
        # the legacy scan selects, in the same order (row-id mapped —
        # keys are per-deployment)
        want, got = legacy["results"][op], indexed["results"][op]
        assert got == want, f"indexed {op} diverged from the legacy scan"
        out.append({
            "op": op,
            "rows": args.keys,
            "hits": len(want),
            "legacy_ms": legacy["timings"][op],
            "indexed_ms": indexed["timings"][op],
        })
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--keys", type=int, default=96)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--seed", type=int, default=13)
    args = ap.parse_args(argv)

    rows = []
    for d in asyncio.run(_drive(args)):
        rows.append(emit(
            f"search latency ({d['op']}, N={d['rows']})",
            1e3 / d["indexed_ms"], "queries/s",
            d["legacy_ms"] / d["indexed_ms"],  # >1 = indexed beats the scan
            **d,
        ))
    return rows


if __name__ == "__main__":
    main()
