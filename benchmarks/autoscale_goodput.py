"""Autoscale goodput: Helmsman self-steering fleet vs a static shape.

The claim behind ISSUE 15: a fleet whose shape is fixed at deploy time
pays for capacity the hotspot is not using (large S) or melts when the
hotspot lands (small S); a Helmsman-steered fleet splits the hot group
onto a warm standby when SLO burn plus a dominant load share persist,
and merges cooled capacity back when the fleet is calm — so goodput per
group-hour beats any static shape on the same schedule.

The harness drives ONE seeded open-loop schedule twice — controller off
(static baseline), then on (adaptive) — against a fresh in-memory
constellation each time:

- a seeded ChaosNet fabric (delivery jitter only — deterministic);
- an OPEN-LOOP arrival schedule (coordinated-omission-safe) with a
  migrating hotspot: phase A hammers a key set clustered on one group's
  ring arc, phase B moves the hotspot to a different group's arc, then a
  cool tail lets the controller fold capacity back;
- a capacity model per group (LANES concurrent service lanes at
  --service-ms each): an op is GOOD iff it completes within --slo-ms of
  its scheduled arrival, and the score divides good ops by the
  time-integral of active group count (group-seconds you pay for).

Reported record (`autoscale goodput`, parsed by benchmarks/sentry.py
--check): value = adaptive goodput per group-second, vs_baseline =
adaptive / static score, detail = split/merge counts, migrated bytes,
and both runs' good/group-second censuses.

Usage: python -m benchmarks.autoscale_goodput [--phase 1.0] [--tail 0.9]
       [--rate 1600] [--static-groups 2] [--seed 23]
"""

from __future__ import annotations

import argparse
import asyncio
import collections
import random
import time

from benchmarks.common import emit


def _pick_hot(map2, map4, splitmap, owner2, new_gid, per_side=3):
    """Keys that form a REAL arc hotspot: clustered on `owner2`'s arc in
    the 2-group ring AND on one group's arc in the 4-group ring, with a
    midpoint split of `owner2` dividing them between old and new owner —
    so every fleet shape feels the same hotspot and a split relieves it."""
    cand = [f"LOAD-{i}" for i in range(400)
            if map2.owner(f"LOAD-{i}") == owner2]
    dom = collections.Counter(map4.owner(k) for k in cand).most_common(1)[0][0]
    cand = [k for k in cand if map4.owner(k) == dom]
    stay = [k for k in cand if splitmap.owner(k) == owner2][:per_side]
    move = [k for k in cand if splitmap.owner(k) == new_gid][:per_side]
    if len(stay) < per_side or len(move) < per_side:
        raise RuntimeError("hot-key selection failed for this ring layout")
    return stay + move


def _schedule(args):
    """One seeded open-loop schedule, identical for both variants."""
    from dds_tpu.shard import ShardMap

    map2 = ShardMap.build(["s0", "s1"], 8)
    map4 = ShardMap.build(["s0", "s1", "s2", "s3"], 8)
    split2 = map2.split("s1", "s2")
    hot_a = _pick_hot(map2, map4, split2, "s1", "s2")
    hot_b = _pick_hot(map2, map4, split2.split("s0", "s3"), "s0", "s3")
    uniform = [f"U-{i}" for i in range(52)]
    universe = uniform + hot_a + hot_b

    rng = random.Random(args.seed)
    sched, t = [], 0.0
    while t < 2 * args.phase:
        t += 1.0 / args.rate
        hot = hot_a if t < args.phase else hot_b
        key = (hot[rng.randrange(len(hot))] if rng.random() < args.p_hot
               else universe[rng.randrange(len(universe))])
        sched.append((t, key))
    while t < 2 * args.phase + args.tail:  # cool tail: back on the A side
        t += 1.0 / args.tail_rate
        key = (hot_a[rng.randrange(len(hot_a))] if rng.random() < 0.7
               else universe[rng.randrange(len(universe))])
        sched.append((t, key))
    return sched, universe


async def _drive(args, sched, universe, adaptive: bool) -> dict:
    from dds_tpu.core.chaos import ChaosNet, LinkFaults
    from dds_tpu.core.transport import InMemoryNet
    from dds_tpu.fleet.helmsman import Helmsman
    from dds_tpu.shard import build_constellation

    net = ChaosNet(InMemoryNet(), seed=args.seed + 7)
    net.default_faults = LinkFaults(jitter=args.jitter_ms / 1e3)
    S = 2 if adaptive else args.static_groups
    const = build_constellation(
        net, shard_count=S, vnodes_per_group=8, seed=args.seed,
        n_active=4, n_sentinent=0, quorum=3,
    )
    r = const.router
    for k in universe:
        await r.write_set(k, [k])

    service, slo = args.service_ms / 1e3, args.slo_ms / 1e3
    lanes: dict[str, asyncio.Semaphore] = {}
    counts: dict[str, int] = {}
    stats = {"good": 0, "backlog": 0, "integral": 0.0}
    t0 = time.perf_counter()

    async def op(due: float, key: str):
        delay = due - (time.perf_counter() - t0)
        if delay > 0:
            await asyncio.sleep(delay)
        stats["backlog"] += 1
        gid = r.owner(key)
        counts[gid] = counts.get(gid, 0) + 1
        sem = lanes.setdefault(gid, asyncio.Semaphore(args.lanes))
        async with sem:
            await asyncio.sleep(service)
        stats["backlog"] -= 1
        if (time.perf_counter() - t0) - due <= slo:
            stats["good"] += 1

    hm = None
    if adaptive:
        hm = Helmsman(
            load_census=lambda: dict(counts),
            slo_alerts=lambda: (["goodput_burn"]
                                if stats["backlog"] > 80 else []),
            split=const.split,
            merge=const.merge,
            moved_bytes=lambda: const.rebalancer.moved_bytes_total,
            reshard_busy=const.rebalancer.lock.locked,
            hot_streak=2, cold_streak=3, hot_share=0.55, cold_share=0.15,
            min_ops=15, cooldown=0.35, max_groups=4, budget_bytes=1 << 30,
        )
    stop = asyncio.Event()

    async def sample():  # group-seconds you pay for, 20ms resolution
        while not stop.is_set():
            stats["integral"] += len(const.groups) * 0.02
            await asyncio.sleep(0.02)

    async def steer():
        while not stop.is_set():
            await hm.step()
            await asyncio.sleep(0.1)

    aux = [asyncio.ensure_future(sample())]
    if hm is not None:
        aux.append(asyncio.ensure_future(steer()))
    await asyncio.gather(*(op(due, key) for due, key in sched))
    stop.set()
    await asyncio.gather(*aux)
    history = list(hm.history) if hm else []
    moved = const.rebalancer.moved_bytes_total
    await const.stop()
    group_s = max(stats["integral"], 1e-9)
    return {
        "good": stats["good"],
        "group_s": round(group_s, 3),
        "score": stats["good"] / group_s,
        "splits": sum(1 for h in history if h["action"] == "split_done"),
        "merges": sum(1 for h in history if h["action"] == "merge_done"),
        "moved_bytes": moved,
        "groups_final": len(const.groups),
    }


def main(argv=None) -> list:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--phase", type=float, default=1.0,
                    help="seconds per hotspot phase (two phases)")
    ap.add_argument("--tail", type=float, default=0.9,
                    help="cool-tail seconds after the phases")
    ap.add_argument("--rate", type=float, default=1600.0,
                    help="open-loop arrivals/s during the phases")
    ap.add_argument("--tail-rate", type=float, default=600.0,
                    help="open-loop arrivals/s during the tail")
    ap.add_argument("--p-hot", type=float, default=0.9,
                    help="fraction of phase traffic on the hot key set")
    ap.add_argument("--lanes", type=int, default=4,
                    help="concurrent service lanes per group")
    ap.add_argument("--service-ms", type=float, default=4.0,
                    help="modeled service time per op")
    ap.add_argument("--slo-ms", type=float, default=120.0,
                    help="an op is GOOD iff done this soon after arrival")
    ap.add_argument("--static-groups", type=int, default=2,
                    help="S for the controller-off baseline fleet")
    ap.add_argument("--jitter-ms", type=float, default=2.0,
                    help="ChaosNet delivery jitter")
    ap.add_argument("--seed", type=int, default=23)
    args = ap.parse_args(argv)

    sched, universe = _schedule(args)
    static = asyncio.run(_drive(args, sched, universe, adaptive=False))
    adaptive = asyncio.run(_drive(args, sched, universe, adaptive=True))

    row = emit(
        "autoscale goodput",
        adaptive["score"],
        "good/group-s",
        adaptive["score"] / max(static["score"], 1e-9),
        phase_s=args.phase,
        tail_s=args.tail,
        rate=args.rate,
        slo_ms=args.slo_ms,
        open_loop=True,
        splits=adaptive["splits"],
        merges=adaptive["merges"],
        moved_bytes=adaptive["moved_bytes"],
        adaptive_good=adaptive["good"],
        adaptive_group_s=adaptive["group_s"],
        static_good=static["good"],
        static_group_s=static["group_s"],
        static_groups=args.static_groups,
        static_score=round(static["score"], 3),
        groups_final=adaptive["groups_final"],
    )
    return [row]


if __name__ == "__main__":
    main()
