"""Prism PC-MM benchmark: server-side Enc(W·x) vs client decrypt-and-compute.

The structural claim behind the analytics plane (arxiv 2504.14497): a
plaintext-matrix x ciphertext-vector product is structured batches of
modexp/modmul, so evaluating it SERVER-SIDE over ciphertexts (one
`backend.matvec` — the weighted-fold kernel or its host twin) competes
with the only alternative the 2017 query set offers: download every
ciphertext, decrypt all K of them client-side, and compute W @ x in
plaintext. The client baseline here is deliberately generous — it pays
only the K CRT decrypts plus the plaintext matmul, with zero network or
re-encryption cost — so `vs_baseline` (client seconds / server seconds)
understates the deployed advantage.

Every trial is decrypt-verified against the plaintext W @ x before it is
timed into a record — a benchmark that silently computes garbage is worse
than a slow one. Weights default to unsigned `--weight-bits`-wide values;
`--signed` mixes in negative weights, which the n-|w| encoding makes
full-n-width exponents — a different (and much heavier) server cost
class, kept out of the default sweep so the records stay comparable.

Emits one `analytics matvec` record per shape via common.emit();
benchmarks/sentry.py --check validates these records in results*.json
(exit 2 on malformed, same contract as the shard-scaling rows).

Usage: python -m benchmarks.analytics_matvec [--shapes 4x64,16x256]
       [--bits 512] [--weight-bits 16] [--backend cpu] [--repeats 3]
       [--signed]
"""

from __future__ import annotations

import argparse
import random

from benchmarks.common import best_of, emit


def _parse_shapes(spec: str) -> list[tuple[int, int]]:
    shapes = []
    for part in spec.split(","):
        r, _, k = part.strip().partition("x")
        shapes.append((int(r), int(k)))
        if shapes[-1][0] < 1 or shapes[-1][1] < 1:
            raise SystemExit(f"bad shape {part!r} (need RxK, both >= 1)")
    return shapes


def main(argv=None) -> list:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--shapes", default="4x64,16x256",
                    help="comma-separated RxK weight-matrix shapes")
    ap.add_argument("--bits", type=int, default=512,
                    help="Paillier modulus bits (local-prime keygen "
                         "below 1024, so no `cryptography` needed)")
    ap.add_argument("--weight-bits", type=int, default=16,
                    help="weight magnitude in bits")
    ap.add_argument("--backend", default="cpu",
                    help="server-side CryptoBackend (cpu | tpu | native)")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--signed", action="store_true",
                    help="mix in negative weights (full-width exponents "
                         "via the n-|w| encoding — a heavier cost class)")
    ap.add_argument("--seed", type=int, default=23)
    args = ap.parse_args(argv)

    from dds_tpu.models.backend import get_backend
    from dds_tpu.models.paillier import PaillierKey

    rng = random.Random(args.seed)
    key = PaillierKey.generate(args.bits)
    pk = key.public
    be = get_backend(args.backend)
    wb = args.weight_bits

    rows = []
    for R, K in _parse_shapes(args.shapes):
        xs = [rng.randrange(1 << 24) for _ in range(K)]
        cs = [pk.encrypt_fast(x) for x in xs]
        lo = -(1 << wb) + 1 if args.signed else 0
        W = [[rng.randrange(lo, 1 << wb) for _ in range(K)] for _ in range(R)]
        enc = pk.matvec_encode(W)

        out = be.matvec(cs, enc, pk.nsquare)  # warm (+ the verified copy)
        got = [key.to_signed(key.decrypt(c)) for c in out]
        want = [sum(w * x for w, x in zip(row, xs)) for row in W]
        if got != want:
            raise SystemExit(
                f"analytics matvec MISCOMPUTED at {R}x{K}: refusing to "
                f"record a timing for a wrong result"
            )
        server_s = best_of(lambda: be.matvec(cs, enc, pk.nsquare),
                           args.repeats)

        def client_side():
            # the pre-Prism path: decrypt everything, matmul in plaintext
            ms = [key.to_signed(m) for m in key.decrypt_batch(cs)]
            return [sum(w * x for w, x in zip(row, ms)) for row in W]

        assert client_side() == want
        client_s = best_of(client_side, args.repeats)

        sign = "signed" if args.signed else "unsigned"
        rows.append(emit(
            f"analytics matvec: Enc(W·x) rows/s @ {R}x{K}, "
            f"{args.bits}-bit, {sign} w{wb}",
            R / server_s, "rows/s",
            vs_baseline=client_s / server_s,
            rows=R, cols=K, paillier_bits=args.bits, weight_bits=wb,
            signed=args.signed, backend=be.name,
            server_ms=round(server_s * 1e3, 3),
            client_ms=round(client_s * 1e3, 3),
        ))
    return rows


if __name__ == "__main__":
    main()
