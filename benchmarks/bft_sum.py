"""BASELINE config #4: 4-replica BFT (f=1) end-to-end encrypted SUM.

Boots the full stack — 4 BFT-ABD replicas (quorum 3 = 2f+1), supervisor,
REST proxy — loads K Paillier-2048 rows through `PutSet` (client-side
encryption, HMAC'd quorum writes), then times `SumAll` requests end-to-end.

Every `SumAll` runs under BFT: with the tag-validated aggregate cache the
proxy validates ALL K cached sets with ONE batched tag-only quorum round
(`AbdClient.read_tags`), then folds the PSSE column homomorphically on the
configured crypto backend. The reference instead re-reads every set through
full 2-round-trip ABD quorums per aggregate (`DDSRestServer.scala:397-446`)
— pass --no-cache to reproduce that behavior. The decrypted result is
checked against the plaintext total before timing.

Two timings per backend:
- sequential: one blocking request at a time (latency; on tunneled TPU
  platforms this is floored by the ~67 ms host<->device round trip);
- concurrent: `--concurrency` in-flight requests (serving throughput; the
  proxy folds in worker threads so device dispatches overlap).

Reported value = homomorphic adds/sec at the best throughput
(requests x (K-1) / wall); vs_baseline = tpu/cpu on this host.

Usage: python -m benchmarks.bft_sum [--k 8192] [--requests 6]
       [--concurrency 8] [--no-cache]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time

from benchmarks.common import emit

PSSE_POS = 2  # canonical schema column 2 is PSSE (client.conf:50-61)

# the BASELINE.json north-star metric, shared with bench.py's headline
METRIC = "end-to-end encrypted SUM adds/sec @ Paillier-2048, 4-replica BFT f=1"


def run_both(k: int, requests: int, concurrency: int, cache: bool = True):
    """Measure both backends on one generated row set; returns (cpu, tpu)
    result dicts. The single orchestration shared by this module's CLI and
    bench.py's worker."""
    from dds_tpu.bench_key import bench_paillier_key

    key = bench_paillier_key()
    enc_rows, total = make_rows(k, key)

    async def go():
        cpu = await _bench_backend(
            "cpu", enc_rows, total, requests, concurrency, cache, key
        )
        tpu = await _bench_backend(
            "tpu", enc_rows, total, requests, concurrency, cache, key
        )
        return cpu, tpu

    return asyncio.run(go())


async def _bench_backend(backend: str, enc_rows: list, total: int, requests: int,
                         concurrency: int, cache: bool, key) -> dict:
    from dds_tpu.http.miniserver import http_request
    from dds_tpu.run import launch
    from dds_tpu.utils.config import DDSConfig

    cfg = DDSConfig()
    cfg.replicas.endpoints = [f"replica-{i}" for i in range(4)]
    cfg.replicas.sentinent = []
    cfg.replicas.byz_quorum_size = 3   # 2f+1, f=1
    cfg.replicas.byz_max_faults = 1
    cfg.recovery.enabled = False       # no spares in this topology; keep timing clean
    cfg.proxy.port = 0
    cfg.proxy.crypto_backend = backend

    dep = await launch(cfg)
    dep.server.cfg.aggregate_cache = cache
    try:
        host, port = cfg.proxy.host, dep.server.cfg.port
        pk = key.public
        K = len(enc_rows)

        # ---- load phase: K PutSets through real ABD quorum writes -------
        t0 = time.perf_counter()
        bodies = [json.dumps({"contents": enc}).encode() for enc in enc_rows]
        sem = asyncio.Semaphore(64)  # bound concurrent sockets during load

        async def put(b):
            async with sem:
                return await http_request(host, port, "POST", "/PutSet", b)

        statuses = await asyncio.gather(*(put(b) for b in bodies))
        assert all(s == 200 for s, _ in statuses), "PutSet failures during load"
        put_s = time.perf_counter() - t0

        # ---- verify: SumAll decrypts to the plaintext total -------------
        target = f"/SumAll?position={PSSE_POS}&nsqr={pk.nsquare}"
        t0 = time.perf_counter()
        status, body = await http_request(host, port, "GET", target, timeout=300.0)
        cold_s = time.perf_counter() - t0
        assert status == 200, f"SumAll failed: {status}"
        got = key.decrypt(int(json.loads(body)["result"]))
        assert got == total, f"SumAll decrypts wrong: {got} != {total}"

        async def timed_get():
            status, _ = await http_request(host, port, "GET", target, timeout=300.0)
            assert status == 200

        # ---- sequential latency (tracer-phased) ------------------------
        from dds_tpu.utils.trace import tracer

        tracer.reset()
        seq = []
        for _ in range(requests):
            t0 = time.perf_counter()
            await timed_get()
            seq.append(time.perf_counter() - t0)
        # per-phase split of the sequential requests: validation round
        # (abd.read_tags), audit quorum reads (abd.fetch), fold dispatch
        # (proxy.fold), whole-aggregate bookkeeping (proxy.fetch_stored)
        phases = {
            name: s["mean_ms"]
            for name, s in tracer.summary().items()
            if name in ("abd.read_tags", "abd.fetch", "proxy.fold",
                        "proxy.fetch_stored", "http.GET.SumAll")
            and "mean_ms" in s
        }

        # ---- concurrent serving throughput -----------------------------
        rounds = max(2, requests // 2)
        t0 = time.perf_counter()
        for _ in range(rounds):
            await asyncio.gather(*(timed_get() for _ in range(concurrency)))
        conc_wall = time.perf_counter() - t0
        per_req = conc_wall / (rounds * concurrency)

        best = min(min(seq), per_req)
        return {
            "backend": backend,
            "adds_per_sec": (K - 1) / best,
            "sumall_ms_seq": min(seq) * 1e3,
            "sumall_ms_concurrent": per_req * 1e3,
            "sumall_ms_cold": cold_s * 1e3,
            "putset_ops_per_sec": K / put_s,
            "phase_mean_ms": phases,
        }
    finally:
        await dep.stop()


def make_rows(k: int, key, pool: int = 64) -> tuple[list, int]:
    """K rows with a Paillier-2048 ciphertext at PSSE_POS. Obfuscators come
    from a precomputed r^n pool (`PaillierPublicKey.blind`) so the loader
    costs one modmul per row, not one 2048-bit modexp; the fold workload and
    decrypt verification are unaffected. Non-PSSE columns are short plains —
    the timed SumAll phase folds only the ciphertext column."""
    pk = key.public
    blinds = [pk.blind() for _ in range(min(pool, k))]
    vals = list(range(1, k + 1))
    rows = [
        [i, f"name-{i}", pk.encrypt(v, rn=blinds[i % len(blinds)]),
         2, "a", "b", "c", "blob"]
        for i, v in enumerate(vals)
    ]
    return rows, sum(vals)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--k", type=int, default=8192, help="stored sets")
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--no-cache", action="store_true",
                    help="reference behavior: full ABD re-read per aggregate")
    args = ap.parse_args(argv)

    cache = not args.no_cache
    cpu, tpu = run_both(args.k, args.requests, args.concurrency, cache)
    return [
        emit(
            METRIC,
            tpu["adds_per_sec"],
            "ops/s",
            tpu["adds_per_sec"] / cpu["adds_per_sec"],
            K=args.k,
            quorum=3,
            aggregate_cache=cache,
            concurrency=args.concurrency,
            sustained=True,
            cpu_adds_per_sec=round(cpu["adds_per_sec"], 1),
            tpu_sumall_ms_seq=round(tpu["sumall_ms_seq"], 2),
            tpu_sumall_ms_concurrent=round(tpu["sumall_ms_concurrent"], 2),
            tpu_sumall_ms_cold=round(tpu["sumall_ms_cold"], 2),
            cpu_sumall_ms_seq=round(cpu["sumall_ms_seq"], 2),
            cpu_sumall_ms_concurrent=round(cpu["sumall_ms_concurrent"], 2),
            putset_ops_per_sec=round(tpu["putset_ops_per_sec"], 1),
            tpu_phase_mean_ms=tpu["phase_mean_ms"],
            cpu_phase_mean_ms=cpu["phase_mean_ms"],
        )
    ]


if __name__ == "__main__":
    main()
