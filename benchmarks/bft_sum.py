"""BASELINE config #4: 4-replica BFT (f=1) end-to-end encrypted SUM.

Boots the full stack — 4 BFT-ABD replicas (quorum 3 = 2f+1), supervisor,
REST proxy — loads K Paillier-2048 rows through `PutSet` (client-side
encryption, HMAC'd quorum writes), then times `SumAll` requests: each one
re-reads every stored set through full ABD quorums (as the reference does,
`dds/http/DDSRestServer.scala:397-446`) and folds the PSSE column
homomorphically on the configured crypto backend. The decrypted result is
checked against the plaintext total before timing.

Rows are encrypted once up front and shared by both backend runs (the
client-side Paillier encrypt is not what this config measures). Default
K=2048 exceeds the tpu backend's adaptive min_device_batch so the fold
runs on-device end-to-end.

Reported value = homomorphic adds/sec sustained end-to-end
((K-1) x SumAll requests/sec); vs_baseline = tpu/cpu on this host.

Usage: python -m benchmarks.bft_sum [--k 2048] [--requests 5]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time

from benchmarks.common import emit

PSSE_POS = 2  # canonical schema column 2 is PSSE (client.conf:50-61)


async def _bench_backend(backend: str, enc_rows: list, total: int, requests: int,
                         provider) -> dict:
    from dds_tpu.http.miniserver import http_request
    from dds_tpu.run import launch
    from dds_tpu.utils.config import DDSConfig

    cfg = DDSConfig()
    cfg.replicas.endpoints = [f"replica-{i}" for i in range(4)]
    cfg.replicas.sentinent = []
    cfg.replicas.byz_quorum_size = 3   # 2f+1, f=1
    cfg.replicas.byz_max_faults = 1
    cfg.recovery.enabled = False       # no spares in this topology; keep timing clean
    cfg.proxy.port = 0
    cfg.proxy.crypto_backend = backend

    dep = await launch(cfg)
    try:
        host, port = cfg.proxy.host, dep.server.cfg.port
        pk = provider.keys.psse.public
        K = len(enc_rows)

        # ---- load phase: K PutSets through real ABD quorum writes -------
        t0 = time.perf_counter()
        bodies = [json.dumps({"contents": enc}).encode() for enc in enc_rows]
        sem = asyncio.Semaphore(64)  # bound concurrent sockets during load

        async def put(b):
            async with sem:
                return await http_request(host, port, "POST", "/PutSet", b)

        statuses = await asyncio.gather(*(put(b) for b in bodies))
        assert all(s == 200 for s, _ in statuses), "PutSet failures during load"
        put_s = time.perf_counter() - t0

        # ---- verify: SumAll decrypts to the plaintext total -------------
        target = f"/SumAll?position={PSSE_POS}&nsqr={pk.nsquare}"
        status, body = await http_request(host, port, "GET", target, timeout=120.0)
        assert status == 200, f"SumAll failed: {status}"
        got = provider.keys.psse.decrypt(int(json.loads(body)["result"]))
        assert got == total, f"SumAll decrypts wrong: {got} != {total}"

        # ---- timing phase ----------------------------------------------
        times = []
        for _ in range(requests):
            t0 = time.perf_counter()
            status, _ = await http_request(host, port, "GET", target, timeout=120.0)
            times.append(time.perf_counter() - t0)
            assert status == 200
        best = min(times)
        return {
            "backend": backend,
            "adds_per_sec": (K - 1) / best,
            "sumall_ms": best * 1e3,
            "putset_ops_per_sec": K / put_s,
        }
    finally:
        await dep.stop()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--k", type=int, default=2048, help="stored sets")
    ap.add_argument("--requests", type=int, default=5)
    args = ap.parse_args(argv)

    from dds_tpu.bench_key import bench_paillier_key
    from dds_tpu.models.facade import HomoProvider
    from dds_tpu.models.keys import HEKeys
    from dds_tpu.utils.config import DataTableConfig

    keys = HEKeys.generate(paillier_bits=512, rsa_bits=1024)  # psse replaced below
    keys = HEKeys(
        ope=keys.ope, che=keys.che, lse=keys.lse,
        psse=bench_paillier_key(), mse=keys.mse, none=keys.none,
    )
    provider = HomoProvider(keys)
    dt = DataTableConfig()

    vals = list(range(1, args.k + 1))
    enc_rows = [
        provider.encrypt_row(
            [i, f"name-{i}", v, 2, "a", "b", "c", "blob"],
            dt.fixed_nr_of_columns,
            dt.fixed_columns_hcrypt,
        )
        for i, v in enumerate(vals)
    ]

    async def go():
        cpu = await _bench_backend("cpu", enc_rows, sum(vals), args.requests, provider)
        tpu = await _bench_backend("tpu", enc_rows, sum(vals), args.requests, provider)
        return cpu, tpu

    cpu, tpu = asyncio.run(go())
    return [
        emit(
            "end-to-end encrypted SUM adds/sec @ Paillier-2048, 4-replica BFT f=1",
            tpu["adds_per_sec"],
            "ops/s",
            tpu["adds_per_sec"] / cpu["adds_per_sec"],
            K=args.k,
            quorum=3,
            fold_path="device" if args.k >= 1024 else
            "host (adaptive: K < min_device_batch=1024)",
            tpu_sumall_ms=round(tpu["sumall_ms"], 2),
            cpu_sumall_ms=round(cpu["sumall_ms"], 2),
            putset_ops_per_sec=round(tpu["putset_ops_per_sec"], 1),
        )
    ]


if __name__ == "__main__":
    main()
