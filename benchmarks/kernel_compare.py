"""Compare fold kernels v1 (fused CIOS) vs v2 (VPU product + MXU REDC).

Correctness-gates v2 against python ints on real device values first,
then times both with the sustained pipelined methodology.

Usage: python -m benchmarks.kernel_compare [--k 65536] [--bits 2048]
"""

from __future__ import annotations

import argparse
import secrets


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--k", type=int, default=65536)
    ap.add_argument("--bits", type=int, default=2048)
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args(argv)

    import jax
    import numpy as np

    from benchmarks.common import emit, sustained_device
    from dds_tpu.bench_key import bench_paillier_key
    from dds_tpu.ops import bignum as bn
    from dds_tpu.ops import mont_mxu as mx
    from dds_tpu.ops import pallas_mont as pm
    from dds_tpu.ops.montgomery import ModCtx

    key = bench_paillier_key(args.bits)
    n2 = key.public.nsquare
    ctx = ModCtx.make(n2)
    mctx = mx.MxuCtx.make(ctx)

    # correctness gate on-device: both kernels agree with python ints
    small = [secrets.randbelow(n2) for _ in range(16)]
    want = 1
    for c in small:
        want = want * c % n2
    sb = bn.ints_to_batch(small, ctx.L)
    got1 = bn.batch_to_ints(np.asarray(pm.reduce_mul(ctx, sb)))[0]
    got2 = bn.batch_to_ints(np.asarray(mx.reduce_mul2(mctx, sb)))[0]
    assert got1 == want, "v1 fold wrong on device"
    assert got2 == want, "v2 fold wrong on device"

    cs = [secrets.randbelow(n2) for _ in range(args.k)]
    resident = jax.device_put(bn.ints_to_batch(cs, ctx.L))
    jax.block_until_ready(resident)

    rows = []
    t1 = sustained_device(lambda: pm.reduce_mul(ctx, resident), repeats=args.repeats)
    t2 = sustained_device(lambda: mx.reduce_mul2(mctx, resident), repeats=args.repeats)
    for name, t in (("v1-cios", t1), ("v2-mxu", t2)):
        rows.append(
            emit(
                f"fold kernel {name} @ {args.bits}-bit Paillier (mod n^2)",
                (args.k - 1) / t,
                "ops/s",
                t1 / t,
                K=args.k,
                limbs=ctx.L,
                fold_ms=round(t * 1e3, 3),
                ns_per_modmul=round(t / args.k * 1e9, 1),
            )
        )
    return rows


if __name__ == "__main__":
    main()
