"""Compare kernels v1 (fused CIOS) vs v2 (VPU product + MXU REDC):
fold (tree reduction) AND batch modexp (square-and-multiply ladder).

Correctness-gates v2 against python ints on real device values first,
then times with the sustained pipelined methodology. This is where the
kernel choice in models/backend.py comes from: v2 wins BOTH ops on real
TPU hardware (folds ~2.3x, modexp ~1.7x sustained) — the MXU REDC
removes most of the VPU multiply work, outweighing the per-multiply HBM
round-trips that v1's VMEM-resident ladder avoids.

Usage: python -m benchmarks.kernel_compare [--k 65536] [--bits 2048]
       [--pow-b 256] [--pow-exp-bits 64]
"""

from __future__ import annotations

import argparse
import secrets


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--k", type=int, default=65536)
    ap.add_argument("--bits", type=int, default=2048)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--pow-b", type=int, default=256, help="modexp batch")
    ap.add_argument("--pow-exp-bits", type=int, default=64)
    args = ap.parse_args(argv)

    import jax
    import numpy as np

    from benchmarks.common import emit, sustained_device
    from dds_tpu.bench_key import bench_paillier_key
    from dds_tpu.ops import bignum as bn
    from dds_tpu.ops import mont_mxu as mx
    from dds_tpu.ops import pallas_mont as pm
    from dds_tpu.ops.montgomery import ModCtx

    key = bench_paillier_key(args.bits)
    n2 = key.public.nsquare
    ctx = ModCtx.make(n2)
    mctx = mx.MxuCtx.make(ctx)

    # correctness gate on-device: both kernels agree with python ints
    small = [secrets.randbelow(n2) for _ in range(16)]
    want = 1
    for c in small:
        want = want * c % n2
    sb = bn.ints_to_batch(small, ctx.L)
    got1 = bn.batch_to_ints(np.asarray(pm.reduce_mul(ctx, sb)))[0]
    got2 = bn.batch_to_ints(np.asarray(mx.reduce_mul2(mctx, sb)))[0]
    assert got1 == want, "v1 fold wrong on device"
    assert got2 == want, "v2 fold wrong on device"

    cs = [secrets.randbelow(n2) for _ in range(args.k)]
    resident = jax.device_put(bn.ints_to_batch(cs, ctx.L))
    jax.block_until_ready(resident)

    # v2 with the fused in-kernel Karatsuba product: the mode is threaded
    # through the jit cache keys (unlike DDS_PROD_TB), so switching the
    # env var in-process measures the real third variant. Save/restore the
    # caller's flag and restore it even if an assert raises.
    import contextlib
    import os

    @contextlib.contextmanager
    def karatsuba_env(value: str | None):
        prior = os.environ.get("DDS_KARATSUBA")
        try:
            if value is None:
                os.environ.pop("DDS_KARATSUBA", None)
            else:
                os.environ["DDS_KARATSUBA"] = value
            yield
        finally:
            if prior is None:
                os.environ.pop("DDS_KARATSUBA", None)
            else:
                os.environ["DDS_KARATSUBA"] = prior

    with karatsuba_env("2"):
        gotf = bn.batch_to_ints(np.asarray(mx.reduce_mul2(mctx, sb)))[0]
        assert gotf == want, "v2-fused-karatsuba fold wrong on device"

    rows = []
    with karatsuba_env(None):
        t1 = sustained_device(lambda: pm.reduce_mul(ctx, resident), repeats=args.repeats)
        t2 = sustained_device(lambda: mx.reduce_mul2(mctx, resident), repeats=args.repeats)
    with karatsuba_env("2"):
        tf = sustained_device(lambda: mx.reduce_mul2(mctx, resident), repeats=args.repeats)
    for name, t in (("v1-cios", t1), ("v2-mxu", t2), ("v2-kfused", tf)):
        rows.append(
            emit(
                f"fold kernel {name} @ {args.bits}-bit Paillier (mod n^2)",
                (args.k - 1) / t,
                "ops/s",
                t1 / t,
                K=args.k,
                limbs=ctx.L,
                fold_ms=round(t * 1e3, 3),
                ns_per_modmul=round(t / args.k * 1e9, 1),
            )
        )

    # ---- batch modexp: the same two multiplies under the exp ladder ----
    B = args.pow_b
    exp = secrets.randbits(args.pow_exp_bits) | 1
    bases = [secrets.randbelow(n2) for _ in range(B)]
    bb = jax.device_put(bn.ints_to_batch(bases, ctx.L))
    jax.block_until_ready(bb)
    want_pow = [pow(b, exp, n2) for b in bases[:4]]
    assert bn.batch_to_ints(np.asarray(pm.pow_mod(ctx, bb, exp)))[:4] == want_pow
    assert bn.batch_to_ints(np.asarray(mx.pow_mod2(mctx, bb, exp)))[:4] == want_pow
    p1 = sustained_device(lambda: pm.pow_mod(ctx, bb, exp), repeats=args.repeats)
    p2 = sustained_device(lambda: mx.pow_mod2(mctx, bb, exp), repeats=args.repeats)
    for name, t in (("v1-cios", p1), ("v2-mxu", p2)):
        rows.append(
            emit(
                f"modexp kernel {name} @ {args.bits}-bit Paillier "
                f"({args.pow_exp_bits}-bit exp)",
                B / t,
                "ops/s",
                p1 / t,
                B=B,
                limbs=ctx.L,
                batch_ms=round(t * 1e3, 3),
            )
        )
    return rows


if __name__ == "__main__":
    main()
