"""Run every BASELINE.md benchmark config and collect the JSON lines.

    python -m benchmarks.run_all [--quick]

`--quick` shrinks batch sizes for a fast smoke pass (CI / CPU-only hosts).
Results also land in benchmarks/results.json for BASELINE.md bookkeeping.
"""

from __future__ import annotations

import argparse
import json
import pathlib


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args(argv)

    from benchmarks import (analytics_matvec, audit_cost, autoscale_goodput,
                            bft_sum, canary_overhead, crossover,
                            decrypt_throughput, encrypt_modexp,
                            fleet_obs_overhead, geo_latency, mixed,
                            multihost_load, overload_goodput, pipe_profile,
                            product, put_concurrency, resident_fold,
                            search_latency, shard_scaling, sweep,
                            tenant_isolation, tiered_fold)

    rows = []
    if args.quick:
        rows += sweep.main(["--k", "1024", "--b", "32", "--sizes", "2048"])
        rows += product.main(["--k", "1024", "--sizes", "1024"])
        rows += bft_sum.main(["--k", "32", "--requests", "2"])
        rows += mixed.main(["--ops", "60"])
        rows += put_concurrency.main(["--ops", "32", "--clients", "1", "4"])
        rows += audit_cost.main(["--k", "256", "--requests", "5"])
        rows += shard_scaling.main(["--ops", "120", "--shards", "1,2"])
        rows += analytics_matvec.main(
            ["--shapes", "2x8", "--bits", "256", "--repeats", "1"]
        )
        rows += overload_goodput.main(
            ["--duration", "1.5", "--keys", "32", "--bits", "1024",
             "--interactive-rate", "15", "--aggregate-rate", "120"]
        )
        rows += tenant_isolation.main(
            ["--duration", "1.5", "--tenants", "4", "--keys-per-tenant", "4",
             "--interactive-rate", "24", "--flood-rate", "32",
             "--bits", "512", "--repeats", "1"]
        )
        rows += multihost_load.main(
            ["--rates", "40,100", "--duration", "1.5", "--keys", "24"]
        )
        rows += fleet_obs_overhead.main(
            ["--rate", "40", "--duration", "1.5", "--keys", "24"]
        )
        rows += pipe_profile.main(
            ["--rate", "40", "--duration", "1.5", "--keys", "24"]
        )
        rows += resident_fold.main(
            ["--k", "64", "--shards", "1,2", "--bits", "256",
             "--repeats", "2"]
        )
        rows += tiered_fold.main(
            ["--max-rows", "32", "--pop-factor", "10", "--hot", "16",
             "--bits", "256", "--repeats", "2"]
        )
        rows += decrypt_throughput.main(
            ["--bits", "512", "--b", "48", "--repeats", "1"]
        )
        rows += search_latency.main(["--keys", "32", "--repeats", "2"])
        rows += autoscale_goodput.main(["--phase", "0.8", "--tail", "0.6"])
        rows += geo_latency.main(
            ["--reads", "24", "--keys", "4", "--scale", "0.05"]
        )
        rows += canary_overhead.main(
            ["--rate", "40", "--duration", "1.5", "--keys", "24",
             "--cadences", "5.0,0.5"]
        )
    else:
        rows += sweep.main([])
        rows += product.main([])
        rows += bft_sum.main([])
        rows += mixed.main([])
        rows += put_concurrency.main([])
        rows += audit_cost.main([])
        rows += crossover.main([])
        rows += encrypt_modexp.main([])
        rows += shard_scaling.main([])
        rows += analytics_matvec.main([])
        rows += overload_goodput.main([])
        rows += tenant_isolation.main([])
        rows += multihost_load.main([])
        rows += fleet_obs_overhead.main([])
        rows += pipe_profile.main([])
        rows += resident_fold.main([])
        rows += tiered_fold.main([])
        rows += decrypt_throughput.main([])
        rows += search_latency.main([])
        rows += autoscale_goodput.main([])
        rows += geo_latency.main([])
        rows += canary_overhead.main([])

    # quick mode is a smoke pass: never clobber real baseline results
    name = "results_quick.json" if args.quick else "results.json"
    out = pathlib.Path(__file__).with_name(name)
    out.write_text(json.dumps(rows, indent=2) + "\n")

    # perf-regression sentry smoke: every suite run re-validates the
    # stored kernel baseline file (emit() above will have grown it), so a
    # corrupted baseline is caught here — including on CPU-only hosts —
    # not at the next TPU gate. --check parses only; it never fails the
    # suite on a perf delta.
    from benchmarks import sentry

    rc = sentry.main(["--check"])
    if rc != 0:
        print(json.dumps({"warning": "kernel baseline failed validation",
                          "sentry_rc": rc}))

    # static-analysis smoke (quick mode only — the full suite is already
    # gated by tier-1's `pytest -m lint`): the Argus passes re-scan the
    # shipped tree against tools/argus/baseline.json, so a hazard landed
    # alongside a benchmark change is caught in the same run. Same
    # exit-code contract as sentry: 1 = new findings, 2 = the baseline
    # itself is malformed; either is a warning here, never a suite abort.
    if args.quick:
        from tools.argus import cli as argus_cli

        argus_rc = argus_cli.main(["--check"])
        if argus_rc != 0:
            print(json.dumps({"warning": "argus static analysis not clean",
                              "argus_rc": argus_rc}))
    return rows


if __name__ == "__main__":
    main()
