"""Stratum tiered-fold benchmark: Zipf working sets past HBM capacity.

The structural claim of the Stratum tier (dds_tpu/storage): a shard
group can hold a ciphertext population ~10x its pool's `max_rows` —
the overflow living in the host-pinned warm cache and the HMAC'd
segment log — while folds over the *hot* subset stay within a small
factor of the no-tiering ceiling, because the Zipf head is resident and
only the tail streams. The pre-Stratum pool would RESET at the first
over-capacity aggregate and every subsequent fold would re-ingest from
scratch.

Per configuration this sweep measures, over one Zipf(θ)-ranked
population `pop_factor` times the pool's `max_rows`:

- ceiling — an all-resident twin plane (max_rows >= population): ingest
  + compile warmup, then the fused fold over the hot subset. The best
  any tiering scheme can do;
- tiered  — `Stratum.fold_groups` over the same operands with the small
  pool: the population is driven through the tiers first (pool
  admission -> eviction-to-warm -> segment overflow), then the hot
  subset folds after promotion warmup.

Every timed fold is equality-gated against the host-int reference fold
first — a tier split that loses bit-for-bit exactness is a benchmark
failure, not a data point. One `tiered fold` record per configuration
lands in results.json via benchmarks/common.emit() (value = tiered
folds/s over the hot subset, vs_baseline = ceiling_ms / tiered_ms — 1.0
means the tier split is free, the acceptance bar is >= 0.9 on the warm
hot set). benchmarks/sentry.py --check validates the records.

Usage: python -m benchmarks.tiered_fold [--max-rows 64] [--pop-factor 10]
       [--hot 32] [--theta 0.9] [--bits 512] [--repeats 5]
"""

from __future__ import annotations

import argparse
import random
import tempfile
import time

from benchmarks.common import emit


def _pyfold(cs, n):
    acc = 1
    for c in cs:
        acc = acc * c % n
    return acc


def _zipf_hot_subset(rng, population, hot, theta, k):
    """`k` draws from a Zipf(theta) rank distribution truncated to the
    `hot` head of `population` — the clt/distribution.py access model,
    inlined so the benchmark has no load-plane dependency."""
    weights = [1.0 / ((i + 1) ** theta) for i in range(hot)]
    total = sum(weights)
    draws = []
    for _ in range(k):
        r = rng.random() * total
        acc = 0.0
        for i, w in enumerate(weights):
            acc += w
            if acc >= r:
                draws.append(population[i])
                break
        else:  # pragma: no cover - float tail
            draws.append(population[hot - 1])
    return draws


def _drive(max_rows: int, pop_factor: int, hot: int, theta: float,
           bits: int, repeats: int, seed: int) -> dict:
    from dds_tpu.resident import ResidentPlane
    from dds_tpu.storage import Stratum

    rng = random.Random(seed)
    modulus = rng.getrandbits(bits) | (1 << (bits - 1)) | 1
    population = [rng.randrange(2, modulus)
                  for _ in range(max_rows * pop_factor)]
    hot = min(hot, max_rows, len(population))
    ops = _zipf_hot_subset(rng, population, hot, theta, k=max(hot, 32))
    expect = _pyfold(ops, modulus)

    # ceiling: the all-resident twin (HBM big enough for everything)
    twin = ResidentPlane(
        initial_rows=max_rows,
        max_rows=max(len(population) * 2, 1 << 16),
    )
    assert twin.fold_groups([("g0", population)], modulus) \
        == _pyfold(population, modulus), "twin diverged from host fold"
    assert twin.fold_groups([("g0", ops)], modulus) == expect

    plane = ResidentPlane(initial_rows=min(8, max_rows), max_rows=max_rows)
    with tempfile.TemporaryDirectory() as tier_dir:
        stratum = Stratum(plane, tier_dir,
                          warm_bytes=max_rows * pop_factor * 16,
                          chunk_rows=max(16, max_rows // 2))
        # drive the whole population through the tiers (admission +
        # eviction-to-warm + warm->segment overflow), equality-gated
        assert stratum.fold_groups([("g0", population)], modulus) \
            == _pyfold(population, modulus), "tier split diverged"
        pool = plane.pool("g0", modulus)
        assert pool.resets == 0, "tiered ingest must never reset the pool"
        # promotion warmup: fold the hot subset until its rows are hot
        for _ in range(3):
            assert stratum.fold_groups([("g0", ops)], modulus) == expect

        ceiling_ms = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            r = twin.fold_groups([("g0", ops)], modulus)
            ceiling_ms.append((time.perf_counter() - t0) * 1e3)
            assert r == expect

        tiered_ms = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            r = stratum.fold_groups([("g0", ops)], modulus)
            tiered_ms.append((time.perf_counter() - t0) * 1e3)
            assert r == expect

        tiers = stratum.stats()["tiers"]
        return {
            "max_rows": max_rows,
            "population": len(population),
            "hot": hot,
            "resets": pool.resets,
            "cold_rows": tiers["cold"]["rows"],
            "warm_rows": tiers["warm"]["rows"],
            "ceiling_ms": min(ceiling_ms),
            "tiered_ms": min(tiered_ms),
        }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--max-rows", type=int, default=64,
                    help="pool capacity (the HBM tier) per group")
    ap.add_argument("--pop-factor", type=int, default=10,
                    help="population = max_rows * pop_factor")
    ap.add_argument("--hot", type=int, default=32,
                    help="Zipf head size the timed folds draw from")
    ap.add_argument("--theta", type=float, default=0.9)
    ap.add_argument("--bits", type=int, default=512)
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--seed", type=int, default=17)
    args = ap.parse_args(argv)

    d = _drive(args.max_rows, args.pop_factor, args.hot, args.theta,
               args.bits, args.repeats, args.seed)
    return [emit(
        f"tiered fold (pop={d['population']}, hbm={d['max_rows']})",
        1e3 / d["tiered_ms"], "folds/s",
        d["ceiling_ms"] / d["tiered_ms"],  # 1.0 = tier split is free
        **d,
    )]


if __name__ == "__main__":
    main()
