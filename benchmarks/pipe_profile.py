"""Chronoscope pipe profile on a real multi-process Meridian fleet.

    python -m benchmarks.pipe_profile [--rate 60] [--duration 2]

Spawns the benchmarks/multihost_load loopback fleet (2 group processes +
1 proxy, Panopticon shipping armed) and drives it with the coordinated-
omission-safe open-loop generator, then scrapes two surfaces the run
exists to validate against each other:

- `GET /profile` — the proxy's local Chronoscope aggregate: per-route
  per-stage critical-path self-times and the attribution coverage
  (fraction of request wall time landing in NAMED stages).
- `GET /fleet/profile` — the Panopticon rollup of every host's
  `dds_pipe_*` gauges, naming the fleet-wide bottleneck stage.

The record carries both top stages plus `agree` (they must name the same
bottleneck for the profile to be trustworthy) and `overhead_pct`: the
goodput cost of profiling, measured by re-running the identical fleet
with DDS_OBS_PIPE=0 in every process. Chronoscope is supposed to be
free-ish (subscriber-side analysis off the request path), so CI watches
that number stays small.

One `pipe profile` record lands via `benchmarks.common.emit`;
`sentry.py --check` validates its shape (exit 2 on malformed).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from benchmarks.multihost_load import Fleet  # noqa: E402


def _stanzas(collector: str) -> tuple[str, str]:
    """(group_extra, proxy_extra) TOML arming the Panopticon plane — the
    fleet rollup needs the groups' gauges shipped to the collector."""
    group = f"""
[obs.fleet]
enabled = true
collector = "{collector}"
flush-interval = 0.1
"""
    proxy = """
[obs.fleet]
enabled = true
stitch-window = 0.5
"""
    return group, proxy


async def _measure(fleet: Fleet, rate: float, duration: float, keys: int,
                   zipf_s: float, seed: int):
    from dds_tpu.fabric.loadgen import OpenLoopLoad

    load = OpenLoopLoad(fleet.proxy_targets, keys=keys, zipf_s=zipf_s,
                        seed=seed, timeout=5.0)
    await load.seed()
    return await load.run(rate, duration)


async def _get_json(port: int, path: str) -> dict:
    from dds_tpu.http.miniserver import http_request

    status, body = await http_request("127.0.0.1", port, "GET", path,
                                      timeout=5.0)
    if status != 200:
        raise RuntimeError(f"GET {path} -> {status}")
    text = body.decode() if isinstance(body, (bytes, bytearray)) else str(body)
    return json.loads(text)


def _pick_route(routes: dict) -> str | None:
    """The PutSet route when profiled, else the busiest route."""
    for route in routes:
        if "PutSet" in route:
            return route
    best = None
    for route, rs in routes.items():
        if best is None or rs.get("count", 0) > routes[best].get("count", 0):
            best = route
    return best


def _run_one(profiler_on: bool, rate: float, duration: float, keys: int,
             zipf_s: float, seed: int):
    """One fleet run; returns (load report, /profile body, /fleet/profile
    body, process count). The off run disables Chronoscope in every
    process via DDS_OBS_PIPE=0 (inherited by the spawned fleet), keeping
    everything else — shipping included — identical."""
    prev = os.environ.get("DDS_OBS_PIPE")
    if not profiler_on:
        os.environ["DDS_OBS_PIPE"] = "0"
    profile = fleet_profile = {}
    try:
        with tempfile.TemporaryDirectory(prefix="pipe-profile-") as workdir:
            fleet = Fleet(workdir)
            fleet.group_extra, fleet.proxy_extra = _stanzas(
                fleet.proxy_transport)
            try:
                fleet.start()
                asyncio.run(fleet.wait_healthy())
                report = asyncio.run(
                    _measure(fleet, rate, duration, keys, zipf_s, seed))
                if profiler_on:
                    # settle one stitch window + ship interval so stitched
                    # trees are profiled and group gauges reach the rollup
                    asyncio.run(asyncio.sleep(1.5))
                    port = fleet.ports["proxy"][0]
                    profile = asyncio.run(_get_json(port, "/profile"))
                    fleet_profile = asyncio.run(
                        _get_json(port, "/fleet/profile"))
            finally:
                fleet.stop()
            procs = len(fleet.gids) + len(fleet.ports["proxy"])
    finally:
        if prev is None:
            os.environ.pop("DDS_OBS_PIPE", None)
        else:
            os.environ["DDS_OBS_PIPE"] = prev
    return report, profile, fleet_profile, procs


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rate", type=float, default=60.0,
                    help="open-loop arrival rate (req/s)")
    ap.add_argument("--duration", type=float, default=2.0)
    ap.add_argument("--keys", type=int, default=32)
    ap.add_argument("--zipf", type=float, default=1.1)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--skip-overhead", action="store_true",
                    help="skip the profiler-off comparison run")
    args = ap.parse_args(argv)

    from benchmarks.common import emit

    on, profile, fleet_profile, procs = _run_one(
        True, args.rate, args.duration, args.keys, args.zipf, args.seed)

    routes = profile.get("routes") or {}
    route = _pick_route(routes)
    rs = routes.get(route) or {}
    stages = {
        k: v.get("p95_ms", 0.0) for k, v in (rs.get("stages") or {}).items()
    }
    top_stage = rs.get("top_stage") or "other"
    f_routes = (fleet_profile.get("fleet") or {}).get("routes") or {}
    f_top = (f_routes.get(route) or {}).get("top_stage") or {}
    fleet_top_stage = f_top.get("stage") or ""
    # both surfaces must finger the same bottleneck for the route; the
    # rollup takes max-across-hosts, so on a local-stage bottleneck the
    # fleet answer is exactly the proxy's own gauge
    agree = bool(fleet_top_stage) and fleet_top_stage == top_stage

    overhead = 0.0
    off_good = None
    if not args.skip_overhead:
        off, _, _, _ = _run_one(
            False, args.rate, args.duration, args.keys, args.zipf, args.seed)
        off_good = off.good
        overhead = 1.0 - (on.good / max(1, off.good))

    return [emit(
        "pipe profile",
        rs.get("wall_p95_ms", 0.0),
        "ms",
        rs.get("coverage", 0.0),
        rate=args.rate,
        duration=args.duration,
        processes=procs,
        open_loop=True,
        route=route or "",
        wall_p95_ms=rs.get("wall_p95_ms", 0.0),
        coverage=rs.get("coverage", 0.0),
        top_stage=top_stage,
        stages=stages,
        fleet_top_stage=fleet_top_stage,
        agree=agree,
        traces_profiled=profile.get("traces_profiled", 0),
        on_good=on.good,
        off_good=off_good,
        overhead_pct=round(overhead * 100.0, 2),
    )]


if __name__ == "__main__":
    main()
