"""BASELINE config #5: OPE range query + Paillier SUM mixed workload.

YCSB-style mix through the full stack (client-side HE, REST proxy, ABD
quorums over the default 9-replica/quorum-5 topology): 20% PutSet, 40% OPE
range searches (Gt/GtEq/Lt/LtEq on the OPE column), 20% SumAll, 10% GetSet,
10% equality search — driven by the schema-aware workload generator, the
same operational-test mechanism the reference uses (SURVEY.md §4.1).

Reports end-to-end client ops/s per crypto backend.

Usage: python -m benchmarks.mixed [--ops 200]
"""

from __future__ import annotations

import argparse
import asyncio

from benchmarks.common import emit

MIX = {
    "put-set": 0.2,
    "search-gt": 0.1, "search-gteq": 0.1, "search-lt": 0.1, "search-lteq": 0.1,
    "sum-all": 0.2,
    "get-set": 0.1,
    "search-eq": 0.1,
}


async def _run_backend(backend: str, ops: int, provider, seed: int,
                       force_device: bool) -> tuple[float, int]:
    from dds_tpu.run import launch, run_workload
    from dds_tpu.utils.config import DDSConfig

    cfg = DDSConfig()
    cfg.proxy.port = 0
    cfg.proxy.crypto_backend = backend
    cfg.recovery.enabled = False       # keep timing clean of proactive restarts
    cfg.client.nr_of_operations = ops
    cfg.client.proportions = dict(MIX)

    dep = await launch(cfg)
    if force_device and hasattr(dep.server.backend, "min_device_batch"):
        dep.server.backend.min_device_batch = 0
    try:
        reports = await run_workload(dep, provider=provider, seed=seed)
        r = reports[0]
        assert r.failed == 0, f"{r.failed} ops failed on {backend}"
        return r.ops_per_second, len(dep.server.stored_keys)
    finally:
        await dep.stop()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", type=int, default=200)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument(
        "--force-device", action="store_true",
        help="set the tpu backend's min_device_batch to 0 so every SumAll "
        "fold runs on-device; default keeps the production adaptive "
        "dispatch, which at this workload's stored-set count (< the 1024 "
        "threshold) routes folds to the host path",
    )
    args = ap.parse_args(argv)

    from dds_tpu.bench_key import bench_paillier_key
    from dds_tpu.models.facade import HomoProvider
    from dds_tpu.models.keys import HEKeys

    keys = HEKeys.generate(paillier_bits=512, rsa_bits=1024)  # psse replaced below
    keys = HEKeys(
        ope=keys.ope, che=keys.che, lse=keys.lse,
        psse=bench_paillier_key(), mse=keys.mse, none=keys.none,
    )
    provider = HomoProvider(keys)

    async def go():
        cpu = await _run_backend("cpu", args.ops, provider, args.seed, False)
        tpu = await _run_backend("tpu", args.ops, provider, args.seed,
                                 args.force_device)
        return cpu, tpu

    (cpu_ops, _), (tpu_ops, stored) = asyncio.run(go())
    return [
        emit(
            "mixed OPE-range + Paillier-SUM workload ops/sec (9 replicas, q=5)",
            tpu_ops,
            "ops/s",
            tpu_ops / cpu_ops,
            ops=args.ops,
            mix=MIX,
            cpu_ops_per_sec=round(cpu_ops, 1),
            stored_sets=stored,
            fold_path="device (forced)" if args.force_device else
            "adaptive (host below min_device_batch=1024)",
        )
    ]


if __name__ == "__main__":
    main()
