"""BASELINE config #5: OPE range query + Paillier SUM mixed workload.

YCSB-style mix through the full stack (client-side HE, REST proxy, ABD
quorums over the default 9-replica/quorum-5 topology): 20% PutSet, 40% OPE
range searches (Gt/GtEq/Lt/LtEq on the OPE column), 20% SumAll, 10% GetSet,
10% equality search — driven by the schema-aware workload generator, the
same operational-test mechanism the reference uses (SURVEY.md §4.1).

Two YCSB-faithful knobs added in r5 (the config-5 re-spec of r4 verdict
#2, justified by benchmarks/crossover.py's curve):
- `--preload K`: a LOAD PHASE stores K encrypted rows before the timed
  transaction phase (YCSB's own shape), so SumAll folds run at a
  realistic store size instead of the ~40 rows the 200-op mix happens to
  accumulate;
- `--clients N`: N concurrent clients (the reference's `Main.scala:
  166-170`), whose concurrent small SumAlls coalesce into shared device
  dispatches (ops/foldmany).

Reports end-to-end aggregate client ops/s per crypto backend.

Usage: python -m benchmarks.mixed [--ops 200] [--preload 4096] [--clients 4]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time

from benchmarks.common import emit

MIX = {
    "put-set": 0.2,
    "search-gt": 0.1, "search-gteq": 0.1, "search-lt": 0.1, "search-lteq": 0.1,
    "sum-all": 0.2,
    "get-set": 0.1,
    "search-eq": 0.1,
}


async def _preload(dep, provider, k: int) -> None:
    """YCSB load phase: store K canonical 8-column rows through PutSet,
    every column encrypted with its schema scheme via the provider (so
    the transaction phase's range/equality searches see real OPE/CHE
    ciphertexts with honest selectivity, not plaintext skew). Only the
    PSSE column bypasses `encrypt_row`, using pooled obfuscators — one
    modmul per row instead of a modexp — to keep the untimed load phase
    cheap; the timed phase is unaffected."""
    from dds_tpu.http.miniserver import http_request

    pk = provider.keys.psse.public
    blinds = [pk.blind() for _ in range(32)]
    host, port = "127.0.0.1", dep.server.cfg.port
    sem = asyncio.Semaphore(64)

    def enc_row(i: int) -> list:
        p = provider
        return [
            p.encrypt(i, "OPE"),
            p.encrypt(f"name-{i}", "CHE"),
            str(pk.encrypt(i, rn=blinds[i % 32])),        # PSSE, pooled
            p.encrypt(2, "MSE"),
            p.encrypt("a", "CHE"), p.encrypt("b", "CHE"), p.encrypt("c", "CHE"),
            p.encrypt(f"blob-{i}", "None"),
        ]

    async def put(i):
        async with sem:
            st, _ = await http_request(
                host, port, "POST", "/PutSet",
                json.dumps({"contents": enc_row(i)}).encode(),
            )
            assert st == 200

    await asyncio.gather(*(put(i) for i in range(k)))


async def _run_backend(backend: str, ops: int, provider, seed: int,
                       force_device: bool, preload: int = 0,
                       clients: int = 1) -> tuple[float, int]:
    from dds_tpu.run import launch, run_workload
    from dds_tpu.utils.config import DDSConfig

    cfg = DDSConfig()
    cfg.proxy.port = 0
    cfg.proxy.crypto_backend = backend
    cfg.recovery.enabled = False       # keep timing clean of proactive restarts
    cfg.client.nr_of_operations = ops
    cfg.client.nr_of_local_clients = clients
    cfg.client.proportions = dict(MIX)

    dep = await launch(cfg)
    if force_device and hasattr(dep.server.backend, "min_device_batch"):
        dep.server.backend.min_device_batch = 0
    try:
        if preload:
            await _preload(dep, provider, preload)
        t0 = time.perf_counter()
        reports = await run_workload(dep, provider=provider, seed=seed)
        wall = time.perf_counter() - t0
        for r in reports:
            assert r.failed == 0, f"{r.failed} ops failed on {backend}"
        total_ops = sum(r.operations for r in reports)
        return total_ops / wall, len(dep.server.stored_keys)
    finally:
        await dep.stop()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", type=int, default=200)
    ap.add_argument("--preload", type=int, default=0,
                    help="YCSB load phase: store this many rows first")
    ap.add_argument("--clients", type=int, default=1,
                    help="concurrent clients (Main.scala:166-170)")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument(
        "--force-device", action="store_true",
        help="set the tpu backend's min_device_batch to 0 so every SumAll "
        "fold runs on-device; default keeps the production adaptive "
        "dispatch, which at this workload's stored-set count (< the 1024 "
        "threshold) routes folds to the host path",
    )
    args = ap.parse_args(argv)

    from dds_tpu.bench_key import bench_paillier_key
    from dds_tpu.models.facade import HomoProvider
    from dds_tpu.models.keys import HEKeys

    keys = HEKeys.generate(paillier_bits=512, rsa_bits=1024)  # psse replaced below
    keys = HEKeys(
        ope=keys.ope, che=keys.che, lse=keys.lse,
        psse=bench_paillier_key(), mse=keys.mse, none=keys.none,
    )
    provider = HomoProvider(keys)

    async def go():
        cpu = await _run_backend("cpu", args.ops, provider, args.seed, False,
                                 args.preload, args.clients)
        tpu = await _run_backend("tpu", args.ops, provider, args.seed,
                                 args.force_device, args.preload, args.clients)
        return cpu, tpu

    (cpu_ops, _), (tpu_ops, stored) = asyncio.run(go())
    return [
        emit(
            "mixed OPE-range + Paillier-SUM workload ops/sec (9 replicas, q=5)",
            tpu_ops,
            "ops/s",
            tpu_ops / cpu_ops,
            ops=args.ops,
            preload=args.preload,
            clients=args.clients,
            mix=MIX,
            cpu_ops_per_sec=round(cpu_ops, 1),
            stored_sets=stored,
            fold_path="device (forced)" if args.force_device else
            "adaptive (host below min_device_batch crossover)",
        )
    ]


if __name__ == "__main__":
    main()
