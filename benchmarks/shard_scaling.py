"""Constellation shard-scaling sweep: fixed op budget, S in {1, 2, 4}.

The structural claim behind sharding (ISSUE 5 / BTS-style lane
partitioning): with a FIXED total replica fleet, aggregate point-op
throughput is capped by one quorum's fan-out — every write costs two
broadcast phases over all n replicas plus quorum replies, so partitioning
the fleet into S independent groups of n/S (each with its own BFT quorum
q = ceil((n + f + 1) / 2), f = floor((n/S - 1) / 3)) divides the per-op
message fan-out by ~S and multiplies throughput accordingly, even on the
single-process test fabric where the event loop is the bottleneck.

The sweep drives a fixed TOTAL budget of put+get ops through the
ShardRouter with `--workers` concurrent clients over the in-memory
fabric (protocol cost only — no HTTP, no crypto: the HE layer is
orthogonal to the sharding claim) and emits one `shard scaling` record
per S via benchmarks/common.emit(), with per-shard op counts in the
detail so imbalance is visible. vs_baseline = throughput relative to
S=1. benchmarks/sentry.py --check parses these records from
results.json as part of its CI smoke.

Usage: python -m benchmarks.shard_scaling [--ops 400] [--shards 1,2,4]
       [--fleet 16] [--workers 8]
"""

from __future__ import annotations

import argparse
import asyncio
import random
import time

from benchmarks.common import emit


def _quorum(n: int) -> tuple[int, int]:
    """Canonical BFT geometry for an n-replica group: f = floor((n-1)/3),
    q = ceil((n + f + 1) / 2)."""
    f = (n - 1) // 3
    return -(-(n + f + 1) // 2), f


async def _drive(shards: int, fleet: int, ops: int, workers: int,
                 seed: int) -> dict:
    from dds_tpu.core.transport import InMemoryNet
    from dds_tpu.shard import build_constellation

    per_group = fleet // shards
    q, f = _quorum(per_group)
    net = InMemoryNet()
    const = build_constellation(
        net, shard_count=shards, n_active=per_group, n_sentinent=0,
        quorum=q, max_faults=f, seed=seed,
    )
    router = const.router
    rng = random.Random(seed)
    keys = [f"BENCH-{i:05d}" for i in range(ops // 2)]
    counter = {"i": 0}

    async def worker():
        while True:
            i = counter["i"]
            if i >= len(keys) * 2:
                return
            counter["i"] = i + 1
            key = keys[i % len(keys)]
            if i < len(keys):
                await router.write_set(key, [key, i])
            else:
                await router.fetch_set(key)

    t0 = time.perf_counter()
    await asyncio.gather(*(worker() for _ in range(workers)))
    wall = time.perf_counter() - t0
    per_shard = {g: len(ks) for g, ks in router.partition_keys(keys).items()}
    await const.stop()
    return {
        "shards": shards,
        "replicas_per_group": per_group,
        "quorum": q,
        "ops": len(keys) * 2,
        "wall_s": round(wall, 4),
        "ops_per_s": (len(keys) * 2) / wall,
        "per_shard_keys": per_shard,
        "rng": rng.random(),  # keep the seeded rng in the record's lineage
    }


def main(argv=None) -> list:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ops", type=int, default=400,
                    help="total op budget per sweep point (puts + gets)")
    ap.add_argument("--shards", default="1,2,4",
                    help="comma-separated shard counts")
    ap.add_argument("--fleet", type=int, default=16,
                    help="TOTAL replicas, partitioned across the groups")
    ap.add_argument("--workers", type=int, default=8,
                    help="concurrent client workers")
    ap.add_argument("--seed", type=int, default=13)
    args = ap.parse_args(argv)

    sweep = [int(s) for s in args.shards.split(",")]
    for s in sweep:
        if args.fleet % s or args.fleet // s < 4:
            raise SystemExit(
                f"--fleet {args.fleet} must divide by S={s} into groups "
                f"of >= 4 replicas"
            )

    rows = []
    base = None
    for s in sweep:
        res = asyncio.run(
            _drive(s, args.fleet, args.ops, args.workers, args.seed)
        )
        res.pop("rng")
        if base is None:
            base = res["ops_per_s"]
        rows.append(emit(
            f"shard scaling: put+get ops/s @ S={s} "
            f"({res['replicas_per_group']}x{s} replicas, q={res['quorum']})",
            res["ops_per_s"], "ops/s",
            vs_baseline=res["ops_per_s"] / base,
            **res,
        ))
    return rows


if __name__ == "__main__":
    main()
