"""Perf-regression sentry CLI: gate CI on per-kernel timing baselines.

Modes (all emit one JSON line to stdout):

    python benchmarks/sentry.py --check [--baseline PATH]
        Parse + validate the stored baseline file only (no kernels run;
        no jax import) — the CPU-only smoke CI runs so a corrupted
        baseline is caught before it silently disables gating.
        Also parses any `shard scaling` (benchmarks/shard_scaling.py),
        `analytics matvec` (benchmarks/analytics_matvec.py),
        `overload goodput` (benchmarks/overload_goodput.py),
        `multihost load` (benchmarks/multihost_load.py),
        `resident fold` (benchmarks/resident_fold.py),
        `tiered fold` (benchmarks/tiered_fold.py),
        `fleet obs` (benchmarks/fleet_obs_overhead.py),
        `pipe profile` (benchmarks/pipe_profile.py),
        `decrypt throughput` (benchmarks/decrypt_throughput.py),
        `search latency` (benchmarks/search_latency.py),
        `autoscale goodput` (benchmarks/autoscale_goodput.py) and
        `tenant isolation` (benchmarks/tenant_isolation.py) records
        in benchmarks/results.json / results_quick.json so a malformed
        scaling, analytics, overload, multihost, fleet-obs, pipe,
        resident, decrypt, search, autoscale or tenant record is
        caught by the same smoke.
        Exit 0 on valid (or absent) files, 2 on a malformed one.

    python benchmarks/sentry.py --record [--baseline PATH] [--repeats N]
        Run the probe workload and (over)write its stats as the new
        baseline. Exit 0.

    python benchmarks/sentry.py [--baseline PATH] [--fresh STATS.json]
                                [--threshold 0.2] [--repeats N]
        Compare a fresh measurement — the probe workload, or a stats
        JSON captured elsewhere (`--fresh`) — against the stored
        baseline. Exit 1 when any kernel phase regressed by more than
        `--threshold` (default 20%), 2 on a malformed baseline/stats
        file, 0 when clean (including "nothing to compare": an empty
        baseline can never fail the gate, it just reports coverage 0).

The probe workload drives `ops.foldmany` (the aggregate-fold kernel
behind `SumAll`) at two fixed shapes; it runs on whatever jax backend is
available, so the same invocation gates CPU CI and TPU perf runs — each
environment keeps its OWN baseline file (a CPU p50 is meaningless
against a TPU one, which is why the kernel key includes shape but the
FILE is per-environment).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from dds_tpu.obs import sentry  # noqa: E402 — stdlib-only import


def probe(repeats: int = 5) -> dict:
    """Deterministic probe workload: a handful of foldmany dispatches at
    two shapes, collected from a fresh tracer ring."""
    from dds_tpu.ops.foldmany import fold_many
    from dds_tpu.utils.trace import tracer

    # a fixed odd modulus (Mersenne 127) keeps ModCtx shapes stable; the
    # UNMEASURED warmup pass eats the trace+compile cost so the recorded
    # dispatch stats are steady-state — a cold compile is ~4x a warm
    # dispatch and would gate on cache temperature, not kernel speed
    n = (1 << 127) - 1
    folds_small = [[3, 5, 7], [11, 13]]
    folds_wide = [[3, 5, 7, 11, 13, 17, 19, 23]] * 4
    fold_many(folds_small, n)
    fold_many(folds_wide, n)
    tracer.reset()
    for _ in range(max(1, repeats)):
        fold_many(folds_small, n)
        fold_many(folds_wide, n)
    return sentry.collect()


def _iter_result_rows(root: str):
    """(file name, record) for every row in the suite result files.
    Unreadable/mis-shaped files raise ValueError — the shared malformed
    contract the per-family checkers map to exit 2."""
    for name in ("results.json", "results_quick.json"):
        path = os.path.join(root, "benchmarks", name)
        if not os.path.exists(path):
            continue
        with open(path) as f:
            try:
                rows = json.load(f)
            except json.JSONDecodeError as e:
                raise ValueError(f"unreadable results file {name}: {e}") from e
        if not isinstance(rows, list):
            raise ValueError(f"malformed results file {name}: expected a list")
        for row in rows:
            yield name, row


def _check_shard_records(root: str = REPO) -> dict:
    """Validate `shard scaling` rows (benchmarks/shard_scaling.py) in the
    suite result files: each must carry a positive ops/s value and a
    detail block naming its shard count and per-shard key split. Returns
    {"rows": n} or raises ValueError on a malformed record — the same
    contract load_baseline has, mapped to exit 2 by --check."""
    found = 0
    for name, row in _iter_result_rows(root):
        if not (isinstance(row, dict)
                and str(row.get("metric", "")).startswith("shard scaling")):
            continue
        detail = row.get("detail")
        ok = (
            isinstance(row.get("value"), (int, float)) and row["value"] > 0
            and isinstance(detail, dict)
            and isinstance(detail.get("shards"), int)
            and detail["shards"] >= 1
            and isinstance(detail.get("per_shard_keys"), dict)
        )
        if not ok:
            raise ValueError(
                f"malformed shard-scaling record in {name}: "
                f"{row.get('metric')!r}"
            )
        found += 1
    return {"rows": found}


def _check_analytics_records(root: str = REPO) -> dict:
    """Validate `analytics matvec` rows (benchmarks/analytics_matvec.py):
    positive rows/s value, a detail block naming the matrix shape, and
    positive server/client timings (the comparison the record exists
    for). Same malformed contract as the shard-scaling rows: exit 2."""
    found = 0
    for name, row in _iter_result_rows(root):
        if not (isinstance(row, dict)
                and str(row.get("metric", "")).startswith("analytics matvec")):
            continue
        detail = row.get("detail")
        ok = (
            isinstance(row.get("value"), (int, float)) and row["value"] > 0
            and isinstance(detail, dict)
            and isinstance(detail.get("rows"), int) and detail["rows"] >= 1
            and isinstance(detail.get("cols"), int) and detail["cols"] >= 1
            and isinstance(detail.get("server_ms"), (int, float))
            and detail["server_ms"] > 0
            and isinstance(detail.get("client_ms"), (int, float))
            and detail["client_ms"] > 0
        )
        if not ok:
            raise ValueError(
                f"malformed analytics record in {name}: "
                f"{row.get('metric')!r}"
            )
        found += 1
    return {"rows": found}


def _check_overload_records(root: str = REPO) -> dict:
    """Validate `overload goodput` rows (benchmarks/overload_goodput.py):
    positive goodput value, a detail block naming the baseline goodput
    (the comparison the record exists for) and the shed census — count
    plus a non-negative shed-latency p95. Same malformed contract as the
    shard/analytics rows: exit 2."""
    found = 0
    for name, row in _iter_result_rows(root):
        if not (isinstance(row, dict)
                and str(row.get("metric", "")).startswith("overload goodput")):
            continue
        detail = row.get("detail")
        ok = (
            isinstance(row.get("value"), (int, float)) and row["value"] > 0
            and isinstance(detail, dict)
            and isinstance(detail.get("baseline_goodput"), (int, float))
            and detail["baseline_goodput"] >= 0
            and isinstance(detail.get("shed_requests"), int)
            and detail["shed_requests"] >= 0
            and isinstance(detail.get("shed_p95_ms"), (int, float))
            and detail["shed_p95_ms"] >= 0
            and isinstance(detail.get("aggregate_rate"), (int, float))
            and detail["aggregate_rate"] > 0
        )
        if not ok:
            raise ValueError(
                f"malformed overload-goodput record in {name}: "
                f"{row.get('metric')!r}"
            )
        found += 1
    return {"rows": found}


def _check_resident_records(root: str = REPO) -> dict:
    """Validate `resident fold` rows (benchmarks/resident_fold.py):
    positive folds/s value and a detail block naming the shard count,
    total rows, and positive warm/cold timings (the warm-vs-marshaling
    comparison the record exists for). Same malformed contract as the
    other row families: exit 2."""
    found = 0
    for name, row in _iter_result_rows(root):
        if not (isinstance(row, dict)
                and str(row.get("metric", "")).startswith("resident fold")):
            continue
        detail = row.get("detail")
        ok = (
            isinstance(row.get("value"), (int, float)) and row["value"] > 0
            and isinstance(detail, dict)
            and isinstance(detail.get("shards"), int)
            and detail["shards"] >= 1
            and isinstance(detail.get("rows"), int) and detail["rows"] >= 1
            and isinstance(detail.get("warm_ms"), (int, float))
            and detail["warm_ms"] > 0
            and isinstance(detail.get("cold_ms"), (int, float))
            and detail["cold_ms"] > 0
        )
        if not ok:
            raise ValueError(
                f"malformed resident-fold record in {name}: "
                f"{row.get('metric')!r}"
            )
        found += 1
    return {"rows": found}


def _check_search_records(root: str = REPO) -> dict:
    """Validate `search latency` rows (benchmarks/search_latency.py):
    positive queries/s value and a detail block naming the op, the store
    size, the hit count, and positive indexed/legacy timings (the
    indexed-vs-scan comparison the record exists for). Same malformed
    contract as the other row families: exit 2."""
    found = 0
    for name, row in _iter_result_rows(root):
        if not (isinstance(row, dict)
                and str(row.get("metric", "")).startswith("search latency")):
            continue
        detail = row.get("detail")
        ok = (
            isinstance(row.get("value"), (int, float)) and row["value"] > 0
            and isinstance(detail, dict)
            and isinstance(detail.get("op"), str) and detail["op"]
            and isinstance(detail.get("rows"), int) and detail["rows"] >= 1
            and isinstance(detail.get("hits"), int) and detail["hits"] >= 0
            and isinstance(detail.get("indexed_ms"), (int, float))
            and detail["indexed_ms"] > 0
            and isinstance(detail.get("legacy_ms"), (int, float))
            and detail["legacy_ms"] > 0
        )
        if not ok:
            raise ValueError(
                f"malformed search-latency record in {name}: "
                f"{row.get('metric')!r}"
            )
        found += 1
    return {"rows": found}


def _check_tiered_records(root: str = REPO) -> dict:
    """Validate `tiered fold` rows (benchmarks/tiered_fold.py): positive
    folds/s value and a detail block naming the pool capacity, a
    population that genuinely exceeds it, a FROZEN reset counter (the
    whole point of eviction-to-warm), and positive ceiling/tiered
    timings (the vs-no-tiering comparison the record exists for). Same
    malformed contract as the other row families: exit 2."""
    found = 0
    for name, row in _iter_result_rows(root):
        if not (isinstance(row, dict)
                and str(row.get("metric", "")).startswith("tiered fold")):
            continue
        detail = row.get("detail")
        ok = (
            isinstance(row.get("value"), (int, float)) and row["value"] > 0
            and isinstance(detail, dict)
            and isinstance(detail.get("max_rows"), int)
            and detail["max_rows"] >= 1
            and isinstance(detail.get("population"), int)
            and detail["population"] > detail["max_rows"]
            and detail.get("resets") == 0
            and isinstance(detail.get("ceiling_ms"), (int, float))
            and detail["ceiling_ms"] > 0
            and isinstance(detail.get("tiered_ms"), (int, float))
            and detail["tiered_ms"] > 0
        )
        if not ok:
            raise ValueError(
                f"malformed tiered-fold record in {name}: "
                f"{row.get('metric')!r}"
            )
        found += 1
    return {"rows": found}


def _check_multihost_records(root: str = REPO) -> dict:
    """Validate `multihost load` rows (benchmarks/multihost_load.py):
    positive good-req/s value, a detail block naming the swept rates, the
    OS-process count (>= 2, or it measured nothing multi-process), the
    open-loop flag, and ordered non-negative p50<=p95<=p99 latencies
    measured from scheduled arrivals. Same malformed contract as the
    other row families: exit 2."""
    found = 0
    for name, row in _iter_result_rows(root):
        if not (isinstance(row, dict)
                and str(row.get("metric", "")).startswith("multihost load")):
            continue
        detail = row.get("detail")
        pcts = []
        if isinstance(detail, dict):
            pcts = [detail.get(k) for k in ("p50_ms", "p95_ms", "p99_ms")]
        ok = (
            isinstance(row.get("value"), (int, float)) and row["value"] > 0
            and isinstance(detail, dict)
            and isinstance(detail.get("rates"), list)
            and len(detail["rates"]) >= 1
            and all(isinstance(r, (int, float)) and r > 0
                    for r in detail["rates"])
            and isinstance(detail.get("processes"), int)
            and detail["processes"] >= 2
            and detail.get("open_loop") is True
            and all(isinstance(p, (int, float)) and p >= 0 for p in pcts)
            and pcts[0] <= pcts[1] <= pcts[2]
        )
        if not ok:
            raise ValueError(
                f"malformed multihost-load record in {name}: "
                f"{row.get('metric')!r}"
            )
        found += 1
    return {"rows": found}


def _check_fleet_obs_records(root: str = REPO) -> dict:
    """Validate `fleet obs` rows (benchmarks/fleet_obs_overhead.py):
    positive good-req/s value and a detail block carrying the shipper-
    on/off goodput pair, the overhead percentage (any sign — noise can
    make the shipper run faster), an OS-process count >= 2, the open-loop
    flag, and the collector's proof-of-life census: sources >= 1 (the
    groups actually shipped), non-negative stitched/dropped counts (drops
    ACCOUNTED is the contract, zero drops is not). Same malformed
    contract as the other row families: exit 2."""
    found = 0
    for name, row in _iter_result_rows(root):
        if not (isinstance(row, dict)
                and str(row.get("metric", "")).startswith("fleet obs")):
            continue
        detail = row.get("detail")
        ok = (
            isinstance(row.get("value"), (int, float)) and row["value"] > 0
            and isinstance(detail, dict)
            and isinstance(detail.get("on_good"), int)
            and detail["on_good"] >= 1
            and isinstance(detail.get("off_good"), int)
            and detail["off_good"] >= 1
            and isinstance(detail.get("overhead_pct"), (int, float))
            and isinstance(detail.get("processes"), int)
            and detail["processes"] >= 2
            and detail.get("open_loop") is True
            and isinstance(detail.get("sources"), int)
            and detail["sources"] >= 1
            and isinstance(detail.get("stitched"), int)
            and detail["stitched"] >= 0
            and isinstance(detail.get("dropped"), int)
            and detail["dropped"] >= 0
        )
        if not ok:
            raise ValueError(
                f"malformed fleet-obs record in {name}: "
                f"{row.get('metric')!r}"
            )
        found += 1
    return {"rows": found}


def _check_decrypt_records(root: str = REPO) -> dict:
    """Validate `decrypt throughput` rows (benchmarks/decrypt_throughput
    .py): positive ops/s value and a detail block naming the key size,
    batch width, positive per-op / batched-host / Sanctum-device rates,
    and verified=True — the decrypt-verified-before-timed contract the
    record exists for. Same malformed contract as the other row
    families: exit 2."""
    found = 0
    for name, row in _iter_result_rows(root):
        if not (isinstance(row, dict)
                and str(row.get("metric", "")).startswith("decrypt throughput")):
            continue
        detail = row.get("detail")
        ok = (
            isinstance(row.get("value"), (int, float)) and row["value"] > 0
            and isinstance(detail, dict)
            and isinstance(detail.get("bits"), int) and detail["bits"] >= 256
            and isinstance(detail.get("batch"), int) and detail["batch"] >= 1
            and isinstance(detail.get("per_op_ops"), (int, float))
            and detail["per_op_ops"] > 0
            and isinstance(detail.get("batched_host_ops"), (int, float))
            and detail["batched_host_ops"] > 0
            and isinstance(detail.get("sanctum_device_ops"), (int, float))
            and detail["sanctum_device_ops"] > 0
            and detail.get("verified") is True
        )
        if not ok:
            raise ValueError(
                f"malformed decrypt-throughput record in {name}: "
                f"{row.get('metric')!r}"
            )
        found += 1
    return {"rows": found}


def _check_autoscale_records(root: str = REPO) -> dict:
    """Validate `autoscale goodput` rows (benchmarks/autoscale_goodput
    .py): positive good-per-group-second value and a detail block
    carrying the static-baseline score (the comparison the record exists
    for), non-negative split/merge/migrated-bytes counts (the controller
    actions the score was bought with), and the open-loop flag. Same
    malformed contract as the other row families: exit 2."""
    found = 0
    for name, row in _iter_result_rows(root):
        if not (isinstance(row, dict)
                and str(row.get("metric", "")).startswith("autoscale goodput")):
            continue
        detail = row.get("detail")
        ok = (
            isinstance(row.get("value"), (int, float)) and row["value"] > 0
            and isinstance(detail, dict)
            and isinstance(detail.get("static_score"), (int, float))
            and detail["static_score"] >= 0
            and isinstance(detail.get("splits"), int)
            and detail["splits"] >= 0
            and isinstance(detail.get("merges"), int)
            and detail["merges"] >= 0
            and isinstance(detail.get("moved_bytes"), int)
            and detail["moved_bytes"] >= 0
            and detail.get("open_loop") is True
        )
        if not ok:
            raise ValueError(
                f"malformed autoscale-goodput record in {name}: "
                f"{row.get('metric')!r}"
            )
        found += 1
    return {"rows": found}


def _check_geo_records(root: str = REPO) -> dict:
    """Validate `geo latency` rows (benchmarks/geo_latency.py): positive
    read-local-vs-quorum speedup and a detail block proving where the
    speedup came from — both p95s, a leased-read count, the mid-run
    revocation flag (the degradation path the record exists to cover),
    ZERO stale reads (a leased read that trailed an acked write would
    make the latency win meaningless), and a named WAN preset so the
    schedule is reproducible. Same malformed contract: exit 2."""
    presets = {"wan-100", "wan-200", "wan-300"}
    found = 0
    for name, row in _iter_result_rows(root):
        if not (isinstance(row, dict)
                and str(row.get("metric", "")).startswith("geo latency")):
            continue
        detail = row.get("detail")
        ok = (
            isinstance(row.get("value"), (int, float)) and row["value"] > 0
            and isinstance(detail, dict)
            and isinstance(detail.get("local_p95_ms"), (int, float))
            and detail["local_p95_ms"] > 0
            and isinstance(detail.get("quorum_p95_ms"), (int, float))
            and detail["quorum_p95_ms"] > 0
            and isinstance(detail.get("reads"), int) and detail["reads"] > 0
            and isinstance(detail.get("leased_reads"), int)
            and detail["leased_reads"] > 0
            and isinstance(detail.get("fallbacks"), int)
            and detail["fallbacks"] >= 0
            and detail.get("revoked_mid_run") is True
            and detail.get("stale_reads") == 0
            and detail.get("wan_preset") in presets
        )
        if not ok:
            raise ValueError(
                f"malformed geo-latency record in {name}: "
                f"{row.get('metric')!r}"
            )
        found += 1
    return {"rows": found}


def _check_pipe_records(root: str = REPO) -> dict:
    """Validate `pipe profile` rows (benchmarks/pipe_profile.py): positive
    p95 wall-time value, a detail block naming the profiled route, a
    coverage fraction in [0, 1], a top stage drawn from the Chronoscope
    taxonomy, a non-empty stages dict of non-negative per-stage p95s, the
    fleet rollup's top stage alongside the agreement flag (the
    local-vs-fleet cross-check the record exists for), an OS-process
    count >= 2, the open-loop flag, and a numeric profiling-overhead
    percentage (any sign — noise can make the profiled run faster). Same
    malformed contract as the other row families: exit 2."""
    from dds_tpu.obs.chronoscope import STAGES

    found = 0
    for name, row in _iter_result_rows(root):
        if not (isinstance(row, dict)
                and str(row.get("metric", "")).startswith("pipe profile")):
            continue
        detail = row.get("detail")
        stages = detail.get("stages") if isinstance(detail, dict) else None
        ok = (
            isinstance(row.get("value"), (int, float)) and row["value"] > 0
            and isinstance(detail, dict)
            and isinstance(detail.get("route"), str) and detail["route"]
            and isinstance(detail.get("wall_p95_ms"), (int, float))
            and detail["wall_p95_ms"] > 0
            and isinstance(detail.get("coverage"), (int, float))
            and 0.0 <= detail["coverage"] <= 1.0
            and detail.get("top_stage") in STAGES
            and isinstance(stages, dict) and stages
            and all(isinstance(v, (int, float)) and v >= 0
                    for v in stages.values())
            and isinstance(detail.get("fleet_top_stage"), str)
            and isinstance(detail.get("agree"), bool)
            and isinstance(detail.get("processes"), int)
            and detail["processes"] >= 2
            and detail.get("open_loop") is True
            and isinstance(detail.get("overhead_pct"), (int, float))
        )
        if not ok:
            raise ValueError(
                f"malformed pipe-profile record in {name}: "
                f"{row.get('metric')!r}"
            )
        found += 1
    return {"rows": found}


def _check_tenant_records(root: str = REPO) -> dict:
    """Validate `tenant isolation` rows (benchmarks/tenant_isolation.py):
    positive victim-p95 value and a detail block carrying both variants'
    p95s (the blast-radius comparison the record exists for), a numeric
    degradation percentage (any sign — best-of runs can come out
    faster), the flooder's shed census (non-negative 429 count bounded
    by its request count, which must be positive or the run flooded
    nothing), at least two tenants, and the open-loop flag. Same
    malformed contract as the other row families: exit 2."""
    found = 0
    for name, row in _iter_result_rows(root):
        if not (isinstance(row, dict)
                and str(row.get("metric", "")).startswith("tenant isolation")):
            continue
        detail = row.get("detail")
        ok = (
            isinstance(row.get("value"), (int, float)) and row["value"] > 0
            and isinstance(detail, dict)
            and isinstance(detail.get("victim_p95_base_ms"), (int, float))
            and detail["victim_p95_base_ms"] > 0
            and isinstance(detail.get("victim_p95_flood_ms"), (int, float))
            and detail["victim_p95_flood_ms"] > 0
            and isinstance(detail.get("degradation_pct"), (int, float))
            and isinstance(detail.get("flooder_requests"), int)
            and detail["flooder_requests"] > 0
            and isinstance(detail.get("flooder_429"), int)
            and 0 <= detail["flooder_429"] <= detail["flooder_requests"]
            and isinstance(detail.get("tenants"), int)
            and detail["tenants"] >= 2
            and detail.get("open_loop") is True
        )
        if not ok:
            raise ValueError(
                f"malformed tenant-isolation record in {name}: "
                f"{row.get('metric')!r}"
            )
        found += 1
    return {"rows": found}


def _check_canary_records(root: str = REPO) -> dict:
    """Validate the Heliograph rows (benchmarks/canary_overhead.py).

    `canary overhead`: positive goodput value, the open-loop flag, the
    default cadence named, a numeric overhead percentage (any sign —
    single-run noise can make the probed run faster), a positive
    baseline goodput, and a non-empty cadence sweep whose every point
    carries goodput, probe census, and its own overhead number.

    `canary drill`: the detection bound the tentpole claims — the
    seeded valid-HMAC corruption caught by decrypt-and-verify within 3
    probe periods, on >= 1 mutated replica, with the passive surface
    green, a Watchtower incident whose trace id matches the ledger
    exemplar, and that exemplar resolvable via `GET /canary`. Same
    malformed contract as the other row families: exit 2."""
    found = 0
    for name, row in _iter_result_rows(root):
        metric = str(row.get("metric", "")) if isinstance(row, dict) else ""
        if metric.startswith("canary overhead"):
            detail = row.get("detail")
            cadences = (detail.get("cadences")
                        if isinstance(detail, dict) else None)
            ok = (
                isinstance(row.get("value"), (int, float)) and row["value"] > 0
                and isinstance(detail, dict)
                and detail.get("open_loop") is True
                and isinstance(detail.get("default_cadence_s"), (int, float))
                and detail["default_cadence_s"] > 0
                and isinstance(detail.get("overhead_pct"), (int, float))
                and isinstance(detail.get("baseline_goodput_rps"),
                               (int, float))
                and detail["baseline_goodput_rps"] > 0
                and isinstance(cadences, dict) and cadences
                and all(
                    isinstance(pt, dict)
                    and isinstance(pt.get("goodput_rps"), (int, float))
                    and isinstance(pt.get("probes"), int) and pt["probes"] >= 0
                    and isinstance(pt.get("probes_ok"), int)
                    and 0 <= pt["probes_ok"] <= pt["probes"]
                    and isinstance(pt.get("overhead_pct"), (int, float))
                    for pt in cadences.values()
                )
                and str(detail["default_cadence_s"]) in cadences
            )
        elif metric.startswith("canary drill"):
            detail = row.get("detail")
            ok = (
                isinstance(row.get("value"), (int, float))
                and 1 <= row["value"] <= 3
                and isinstance(detail, dict)
                and isinstance(detail.get("detected_within_periods"), int)
                and detail["detected_within_periods"] == row["value"]
                and isinstance(detail.get("replicas_mutated"), int)
                and detail["replicas_mutated"] >= 1
                and detail.get("passive_green") is True
                and detail.get("verdict") == "wrong_answer"
                and isinstance(detail.get("trace_id"), str)
                and detail["trace_id"]
                and isinstance(detail.get("watchtower_incidents"), int)
                and detail["watchtower_incidents"] >= 1
                and detail.get("incident_trace_match") is True
                and detail.get("exemplar_resolved") is True
            )
        else:
            continue
        if not ok:
            raise ValueError(
                f"malformed canary record in {name}: {metric!r}"
            )
        found += 1
    return {"rows": found}


def _load_fresh(path: str) -> dict:
    """A stats JSON: either the baseline schema or a bare kernels dict."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict) and isinstance(data.get("kernels"), dict):
        return data["kernels"]
    if isinstance(data, dict):
        return data
    raise ValueError(f"malformed fresh stats {path!r}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: DDS_KERNEL_BASELINE or "
                         "benchmarks/kernel_baseline.json)")
    ap.add_argument("--check", action="store_true",
                    help="validate the baseline file and exit")
    ap.add_argument("--record", action="store_true",
                    help="run the probe and store its stats as the baseline")
    ap.add_argument("--fresh", default=None,
                    help="compare this stats JSON instead of running the probe")
    ap.add_argument("--threshold", type=float, default=0.20,
                    help="regression gate as a fraction (default 0.20)")
    ap.add_argument("--floor-ms", type=float, default=0.05,
                    help="ignore deltas below this many ms (timer noise)")
    ap.add_argument("--repeats", type=int, default=5,
                    help="probe workload repetitions")
    args = ap.parse_args(argv)

    path = str(sentry.baseline_path(args.baseline))
    try:
        baseline = sentry.load_baseline(args.baseline)
    except ValueError as e:
        print(json.dumps({"ok": False, "baseline": path, "error": str(e)}))
        return 2

    if args.check:
        try:
            shard = _check_shard_records()
            analytics = _check_analytics_records()
            overload = _check_overload_records()
            multihost = _check_multihost_records()
            fleet_obs = _check_fleet_obs_records()
            pipe = _check_pipe_records()
            resident = _check_resident_records()
            tiered = _check_tiered_records()
            decrypt = _check_decrypt_records()
            search = _check_search_records()
            autoscale = _check_autoscale_records()
            geo = _check_geo_records()
            tenant = _check_tenant_records()
            canary = _check_canary_records()
        except ValueError as e:
            print(json.dumps({"ok": False, "baseline": path,
                              "error": str(e)}))
            return 2
        print(json.dumps({
            "ok": True, "mode": "check", "baseline": path,
            "kernels": len(baseline), "exists": bool(baseline),
            "shard_scaling_rows": shard["rows"],
            "analytics_rows": analytics["rows"],
            "overload_rows": overload["rows"],
            "multihost_rows": multihost["rows"],
            "fleet_obs_rows": fleet_obs["rows"],
            "pipe_rows": pipe["rows"],
            "resident_rows": resident["rows"],
            "tiered_rows": tiered["rows"],
            "decrypt_rows": decrypt["rows"],
            "search_rows": search["rows"],
            "autoscale_rows": autoscale["rows"],
            "geo_rows": geo["rows"],
            "tenant_rows": tenant["rows"],
            "canary_rows": canary["rows"],
        }))
        return 0

    if args.record:
        stats = probe(args.repeats)
        sentry.save_baseline(stats, args.baseline, overwrite=True)
        print(json.dumps({
            "ok": True, "mode": "record", "baseline": path,
            "kernels": sorted(stats),
        }))
        return 0

    try:
        fresh = _load_fresh(args.fresh) if args.fresh else probe(args.repeats)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(json.dumps({"ok": False, "baseline": path, "error": str(e)}))
        return 2

    findings = sentry.compare(
        baseline, fresh, threshold=args.threshold, floor_ms=args.floor_ms
    )
    compared = sorted(set(baseline) & set(fresh))
    print(json.dumps({
        "ok": not findings,
        "mode": "compare",
        "baseline": path,
        "threshold": args.threshold,
        "compared": compared,
        "uncovered": sorted(set(fresh) - set(baseline)),
        "regressions": findings,
    }))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
