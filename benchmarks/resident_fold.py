"""Lodestone resident-fold benchmark: warm fused aggregates vs per-fold
marshaling.

The structural claim of ISSUE 9 (and the HE-accelerator literature it
follows — BTS, arxiv 2112.15479): aggregate throughput comes from keeping
partitioned ciphertext lanes memory-resident and host<->device traffic
index-only. The pre-Lodestone sharded aggregate re-marshals every
operand's limbs (int -> (K, L) uint32) and dispatches S independent folds
per request; the resident plane gathers each group's rows from its pinned
pool and dispatches ONE fused gather+fold.

Per shard count S this sweep measures, over the same operand sets and the
same modulus:

- cold  — the per-fold-marshaling baseline: per aggregate, S separate
  `ints_to_batch` conversions + S `ModCtx.reduce_mul` dispatches + the
  host `combine_partials` tail (exactly what the scatter path did);
- warm  — `ResidentPlane.fold_groups` after ingest + compile warmup:
  index lookup, one fused dispatch.

Both are verified against the host-int reference fold before timing, and
one `resident fold` record per S lands in results.json via
benchmarks/common.emit() (value = warm aggregates/s, vs_baseline =
cold_ms / warm_ms). benchmarks/sentry.py --check validates the records.

Usage: python -m benchmarks.resident_fold [--k 256] [--shards 1,4]
       [--bits 512] [--repeats 5]
"""

from __future__ import annotations

import argparse
import random
import time

from benchmarks.common import emit


def _pyfold(cs, n):
    acc = 1
    for c in cs:
        acc = acc * c % n
    return acc


def _drive(S: int, k: int, bits: int, repeats: int, seed: int) -> dict:
    import jax.numpy as jnp
    import numpy as np

    from dds_tpu.ops import bignum as bn
    from dds_tpu.ops.montgomery import ModCtx
    from dds_tpu.parallel.mesh import combine_partials
    from dds_tpu.resident import ResidentPlane

    rng = random.Random(seed)
    modulus = rng.getrandbits(bits) | (1 << (bits - 1)) | 1
    per_group = max(2, k // S)
    parts = [
        (f"s{i}", [rng.randrange(1, modulus) for _ in range(per_group)])
        for i in range(S)
    ]
    allops = [c for _, ops in parts for c in ops]
    expect = _pyfold(allops, modulus)
    ctx = ModCtx.make(modulus)

    def cold_once() -> int:
        # the per-fold marshaling baseline: host limbs + one dispatch per
        # group + host tail combine (the pre-Lodestone scatter path)
        partials = []
        for _, ops in parts:
            batch = bn.ints_to_batch([c % modulus for c in ops], ctx.L)
            out = ctx.reduce_mul(jnp.asarray(batch))
            partials.append(bn.limbs_to_int(np.asarray(out)[0]))
        return combine_partials(partials, modulus)

    plane = ResidentPlane(initial_rows=256,
                          max_rows=max(256, 1 << (per_group * S).bit_length()))

    # correctness gate before any timing: both paths must equal the host
    # reference fold bit-for-bit
    assert cold_once() == expect, "cold baseline diverged from host fold"
    warm0 = plane.fold_groups(parts, modulus)  # ingest + compile warmup
    assert warm0 == expect, "resident fused fold diverged from host fold"

    cold_ms = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        cold_once()
        cold_ms.append((time.perf_counter() - t0) * 1e3)
    cold_once()  # keep compile caches warm symmetry

    warm_ms = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        r = plane.fold_groups(parts, modulus)
        warm_ms.append((time.perf_counter() - t0) * 1e3)
        assert r == expect
    return {
        "shards": S,
        "rows": len(allops),
        "cold_ms": min(cold_ms),
        "warm_ms": min(warm_ms),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--k", type=int, default=256,
                    help="total operands per aggregate (split across S)")
    ap.add_argument("--shards", default="1,4")
    ap.add_argument("--bits", type=int, default=512)
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--seed", type=int, default=9)
    args = ap.parse_args(argv)

    rows = []
    for S in [int(s) for s in args.shards.split(",") if s.strip()]:
        d = _drive(S, args.k, args.bits, args.repeats, args.seed)
        rows.append(emit(
            f"resident fold (S={S}, K={d['rows']})",
            1e3 / d["warm_ms"], "folds/s",
            d["cold_ms"] / d["warm_ms"],  # >1 = warm beats marshaling
            **d,
        ))
    return rows


if __name__ == "__main__":
    main()
