"""Tenant isolation: noisy-neighbor blast radius under Bastion.

The claim behind the Bastion tentpole: with tenancy enabled, one
tenant flooding the aggregate plane is contained by its OWN admission
buckets (weighted-fair per-tenant refill) — the flooder absorbs 429s in
microseconds while every other tenant's interactive latency barely
moves. Without isolation the flood would ride the shared class bucket
and the deadline machinery, and everyone's p95 would follow it up.

The harness drives ONE seeded Zipf-over-tenants schedule twice against
a fresh tenancy-enabled 4-replica deployment each time:

- a population of victim tenants whose per-arrival tenant is drawn from
  a seeded Zipf distribution (rank-weighted 1/r^s — the skewed
  multi-tenant traffic shape), each doing interactive point reads on
  ITS OWN keys plus an occasional per-tenant aggregate fold;
- run B adds a flooder tenant driving `SumAll` folds at several times
  the aggregate admission rate, starting 2 s BEFORE the victim window
  so the measurement sees the steady shed state (flood 429s answer in
  microseconds), not the token bucket's initial admit burst. The victim
  schedule is drawn from the same seeded rng stream in both runs, so
  the only delta IS the flood. Each variant runs `--repeats` times
  interleaved and reports its MIN p95 — the suite's best-of discipline,
  which filters host-scheduler noise (these boxes are often 1-core).

Reported record (`tenant isolation`, parsed by benchmarks/sentry.py
--check): value = victim interactive p95 under flood (ms), vs_baseline
= flood p95 / no-flood p95 (the blast-radius ratio the acceptance bar
caps at 1.10), detail = both p95s, the degradation percentage, the
flooder's shed census (429s must dominate its outcomes), and both
runs' full status censuses.

Usage: python -m benchmarks.tenant_isolation [--duration 3]
       [--tenants 5] [--keys-per-tenant 8] [--interactive-rate 40]
       [--flood-rate 120] [--seed 23]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random
import time

from benchmarks.common import emit


def _percentile(xs: list[float], q: float) -> float:
    """Nearest-rank percentile; 0 for an empty sample."""
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, max(0, int(q * len(xs)) - 1))]


def _config(args):
    from dds_tpu.utils.config import DDSConfig

    cfg = DDSConfig()
    cfg.replicas.endpoints = [f"replica-{i}" for i in range(4)]
    cfg.replicas.sentinent = []
    cfg.replicas.byz_quorum_size = 3
    cfg.replicas.byz_max_faults = 1
    cfg.proxy.port = 0
    cfg.proxy.request_budget = args.budget
    cfg.proxy.intranet_request_timeout = args.budget / 2
    # quiet fabric: the bench measures isolation, not recovery timers
    cfg.recovery.enabled = False
    cfg.recovery.anti_entropy_enabled = False
    cfg.obs.audit_enabled = False
    cfg.obs.slo_fast_window = 1.0
    cfg.obs.slo_slow_window = 2.0
    # Bastion on: per-tenant buckets, striped planes, tenant attribution
    cfg.tenancy.enabled = True
    cfg.admission.enabled = True
    cfg.admission.eval_interval = 0.2
    cfg.admission.shed_hold = 4
    # the aggregate class is where the flood lands: a few folds/s
    # sustained fleet-wide; the weighted-fair rebalance contracts the
    # flooder's share under contention while victims keep theirs
    cfg.admission.aggregate_rate = args.admit_aggregate_rate
    cfg.admission.aggregate_burst = args.admit_aggregate_rate
    cfg.admission.interactive_rate = args.interactive_rate * 4
    cfg.admission.interactive_burst = args.interactive_rate * 8
    return cfg


def _zipf_weights(n: int, s: float) -> list[float]:
    w = [1.0 / (r ** s) for r in range(1, n + 1)]
    total = sum(w)
    return [x / total for x in w]


async def _drive(args, flood: bool) -> dict:
    from dds_tpu.http.miniserver import http_request
    from dds_tpu.run import launch

    cfg = _config(args)
    dep = await launch(cfg)
    host, port = cfg.proxy.host, dep.server.cfg.port
    modulus = (1 << args.bits) - 159  # fixed odd fold modulus

    victims = [f"tenant-{i:02d}" for i in range(args.tenants)]
    weights = _zipf_weights(args.tenants, args.zipf_s)

    async def call(method, target, obj=None, tenant=None):
        body = json.dumps(obj).encode() if obj is not None else None
        hdrs = {"x-dds-tenant": tenant} if tenant else None
        t0 = time.perf_counter()
        try:
            status, _ = await http_request(
                host, port, method, target, body, headers=hdrs,
                timeout=args.budget + 2.0,
            )
        except (OSError, asyncio.TimeoutError, EOFError, ConnectionError):
            status = -1  # client-visible failure (timeout/reset)
        return status, time.perf_counter() - t0

    # seed each tenant's keyspace: K records of `bits`-bit residues
    # standing in for Paillier ciphertexts (the HE layer is orthogonal
    # to the isolation claim); ownership is claimed by the writing
    # tenant, so every later fold is a per-tenant projection
    seed_rng = random.Random(args.seed)
    keys: dict[str, list[str]] = {t: [] for t in victims + ["flood"]}
    for tenant in keys:
        for _ in range(args.keys_per_tenant):
            status, body = await http_request(
                host, port, "POST", "/PutSet",
                json.dumps({"contents": [
                    str(seed_rng.getrandbits(args.bits) % modulus)
                ]}).encode(),
                headers={"x-dds-tenant": tenant}, timeout=10.0,
            )
            if status != 200:
                raise RuntimeError(f"store seeding failed with {status}")
            keys[tenant].append(body.decode())

    # open-loop victim schedule, identical for both variants: tenant
    # choice, op mix, and arrival jitter all come from the SAME seeded
    # rng stream, so run B differs from run A only by the flood
    sched_rng = random.Random(args.seed + 1)
    schedule: list[tuple[str, str, float]] = []
    t = 0.0
    while t < args.duration:
        tenant = sched_rng.choices(victims, weights=weights)[0]
        op = "agg" if sched_rng.random() < args.victim_agg_frac else "point"
        schedule.append((tenant, op, t))
        t += sched_rng.uniform(0.5, 1.5) / args.interactive_rate

    results: list[tuple[str, str, int, float]] = []

    async def fire(tenant: str, op: str):
        if op == "point":
            key = keys[tenant][sched_rng.randrange(len(keys[tenant]))]
            status, lat = await call("GET", f"/GetSet/{key}", tenant=tenant)
        else:
            status, lat = await call(
                "GET", f"/SumAll?position=0&nsqr={modulus}", tenant=tenant
            )
        results.append((tenant, op, status, lat))

    flooder_census: dict[str, int] = {}
    flood_task = None
    if flood:
        async def flood_one():
            status, _lat = await call(
                "GET", f"/SumAll?position=0&nsqr={modulus}", tenant="flood"
            )
            label = str(status) if status > 0 else "client_error"
            flooder_census[label] = flooder_census.get(label, 0) + 1

        async def flood_driver():
            frng = random.Random(args.seed + 99)
            fpending = []
            ft0, ft = time.perf_counter(), 0.0
            while ft < args.duration + 2.0:
                delay = ft - (time.perf_counter() - ft0)
                if delay > 0:
                    await asyncio.sleep(delay)
                fpending.append(asyncio.ensure_future(flood_one()))
                ft += frng.uniform(0.5, 1.5) / args.flood_rate
            await asyncio.gather(*fpending)

        flood_task = asyncio.ensure_future(flood_driver())
        # lead-in: let the flood drain the aggregate bucket's initial
        # burst, so the victim window sees the steady shed state the
        # claim is about, not the admit transient
        await asyncio.sleep(2.0)
    t0 = time.perf_counter()
    pending = []
    for tenant, op, at in schedule:
        delay = at - (time.perf_counter() - t0)
        if delay > 0:
            await asyncio.sleep(delay)
        pending.append(asyncio.ensure_future(fire(tenant, op)))
    await asyncio.wait_for(asyncio.gather(*pending), args.budget + 10.0)
    wall = time.perf_counter() - t0
    if flood_task is not None:
        await asyncio.wait_for(flood_task, args.budget + 30.0)
    shed = dep.server.admission.shed_tenants() if dep.server.admission else []
    await dep.stop()

    victim_census: dict[str, int] = {}
    for _tenant, _op, status, _lat in results:
        label = str(status) if status > 0 else "client_error"
        victim_census[label] = victim_census.get(label, 0) + 1
    victim_lat = [
        lat for _tenant, op, status, lat in results
        if op == "point" and status == 200
    ]
    return {
        "wall_s": round(wall, 3),
        "victim_p50_ms": round(_percentile(victim_lat, 0.50) * 1e3, 3),
        "victim_p95_ms": round(_percentile(victim_lat, 0.95) * 1e3, 3),
        "victim_points": len(victim_lat),
        "census": {"victims": victim_census, "flooder": flooder_census},
        "flooder_requests": sum(flooder_census.values()),
        "flooder_429": flooder_census.get("429", 0),
        "shed_tenants": shed,
    }


def main(argv=None) -> list:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--duration", type=float, default=3.0,
                    help="open-loop schedule length (s) per variant")
    ap.add_argument("--tenants", type=int, default=5,
                    help="victim tenant population (Zipf-ranked)")
    ap.add_argument("--zipf-s", type=float, default=1.2,
                    help="Zipf skew exponent over tenant ranks")
    ap.add_argument("--keys-per-tenant", type=int, default=8,
                    help="stored records per tenant keyspace")
    ap.add_argument("--interactive-rate", type=float, default=40.0,
                    help="victim arrivals/s across the population")
    ap.add_argument("--victim-agg-frac", type=float, default=0.1,
                    help="fraction of victim arrivals that are folds")
    ap.add_argument("--flood-rate", type=float, default=48.0,
                    help="flooder SumAll arrivals/s (the overload; several "
                         "times the aggregate admission rate)")
    ap.add_argument("--admit-aggregate-rate", type=float, default=2.0,
                    help="Bulwark aggregate class rate/burst (tight, so "
                         "admitted flood folds cannot crowd the loop)")
    ap.add_argument("--budget", type=float, default=1.5,
                    help="proxy request budget (s)")
    ap.add_argument("--bits", type=int, default=1024,
                    help="stored ciphertext width")
    ap.add_argument("--repeats", type=int, default=2,
                    help="interleaved runs per variant; each variant "
                         "reports its MIN p95 (best-of filters host "
                         "scheduler noise, the suite's best_of discipline)")
    ap.add_argument("--seed", type=int, default=23)
    args = ap.parse_args(argv)

    base_runs, flood_runs = [], []
    for _ in range(max(1, args.repeats)):
        base_runs.append(asyncio.run(_drive(args, flood=False)))
        flood_runs.append(asyncio.run(_drive(args, flood=True)))
    base = min(base_runs, key=lambda r: r["victim_p95_ms"])
    flooded = min(flood_runs, key=lambda r: r["victim_p95_ms"])

    base_p95 = max(base["victim_p95_ms"], 1e-9)
    ratio = flooded["victim_p95_ms"] / base_p95
    degradation_pct = round((ratio - 1.0) * 100.0, 2)
    row = emit(
        "tenant isolation victim p95",
        flooded["victim_p95_ms"],
        "ms",
        ratio,
        duration_s=args.duration,
        tenants=args.tenants,
        zipf_s=args.zipf_s,
        interactive_rate=args.interactive_rate,
        flood_rate=args.flood_rate,
        victim_p95_base_ms=base["victim_p95_ms"],
        victim_p95_flood_ms=flooded["victim_p95_ms"],
        degradation_pct=degradation_pct,
        isolated=bool(degradation_pct < 10.0),
        flooder_requests=flooded["flooder_requests"],
        flooder_429=flooded["flooder_429"],
        shed_tenants=flooded["shed_tenants"],
        open_loop=True,
        repeats=max(1, args.repeats),
        base_p95_runs=[r["victim_p95_ms"] for r in base_runs],
        flood_p95_runs=[r["victim_p95_ms"] for r in flood_runs],
        baseline=base,
        flood=flooded,
    )
    return [row]


if __name__ == "__main__":
    main()
