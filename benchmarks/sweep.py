"""BASELINE config #2: Paillier key-size sweep 2048/3072/4096.

For each key size, measures the two homomorphic primitives the proxy's
extended API is built from (`dds/http/DDSRestServer.scala:385,423` and the
scalar path of Paillier):

- batched homomorphic SUM: modular-product fold of K ciphertexts mod n^2
  (cpu python-int fold vs one fused TPU Montgomery tree-reduction over
  device-resident limbs);
- batched scalar-MUL: c^k mod n^2 over a batch of B ciphertexts with a
  shared 64-bit scalar (cpu pow() loop vs one batched TPU modexp ladder).

Both primitives are decrypt-verified on a sub-batch before timing.

Usage: python -m benchmarks.sweep [--k 16384] [--b 256] [--sizes 2048,3072,4096]
"""

from __future__ import annotations

import argparse
import secrets


from benchmarks.common import best_of, emit, sustained_device

SCALAR_BITS = 64


def sweep_one(bits: int, K: int, B: int, repeats: int = 3) -> list[dict]:
    import jax

    from dds_tpu.bench_key import bench_paillier_key
    from dds_tpu.models.backend import CpuBackend, TpuBackend
    from dds_tpu.ops import bignum as bn
    from dds_tpu.ops.montgomery import ModCtx

    key = bench_paillier_key(bits)
    pk = key.public
    n2 = pk.nsquare
    # min_device_batch=0: correctness gates must exercise the DEVICE fold
    # even on small batches (the default adaptive dispatch would route them
    # to the host path)
    cpu, tpu = CpuBackend(), TpuBackend(min_device_batch=0)
    rows = []

    # correctness gates on real ciphertexts
    vals = [secrets.randbelow(1 << 32) for _ in range(16)]
    cts = [pk.encrypt(v) for v in vals]
    assert key.decrypt(tpu.modmul_fold(cts, n2)) == sum(vals)
    k_scalar = secrets.randbits(SCALAR_BITS)
    powed = tpu.powmod_batch(cts[:4], k_scalar, n2)
    for v, c in zip(vals[:4], powed):
        assert key.decrypt(c) == (v * k_scalar) % pk.n

    # ---- SUM fold -------------------------------------------------------
    cs = [secrets.randbelow(n2) for _ in range(K)]
    cpu_s = best_of(lambda: cpu.modmul_fold(cs, n2), repeats)
    cpu_ops = (K - 1) / cpu_s

    ctx = ModCtx.make(n2)
    resident = jax.device_put(bn.ints_to_batch(cs, ctx.L))
    jax.block_until_ready(resident)
    tpu_s = sustained_device(
        lambda: tpu.reduce_mul_device(ctx, resident), repeats=repeats
    )
    tpu_ops = (K - 1) / tpu_s
    rows.append(
        emit(
            f"encrypted SUM ops/sec @ Paillier-{bits}",
            tpu_ops,
            "ops/s",
            tpu_ops / cpu_ops,
            K=K,
            limbs=ctx.L,
            cpu_ops_per_sec=round(cpu_ops, 1),
            tpu_fold_ms=round(tpu_s * 1e3, 2),
            cpu_fold_ms=round(cpu_s * 1e3, 2),
        )
    )

    # ---- scalar-MUL (batched modexp, shared exponent) -------------------
    bases = [secrets.randbelow(n2) for _ in range(B)]
    cpu_s = best_of(lambda: [pow(c, k_scalar, n2) for c in bases], repeats)
    cpu_ops = B / cpu_s

    batch = jax.device_put(bn.ints_to_batch(bases, ctx.L))
    jax.block_until_ready(batch)
    if tpu.pallas:
        from dds_tpu.ops import pallas_mont

        run = lambda: pallas_mont.pow_mod(ctx, batch, k_scalar)
    else:
        run = lambda: ctx.pow_mod(batch, k_scalar)
    tpu_s = sustained_device(run, R=8, repeats=repeats)
    tpu_ops = B / tpu_s
    rows.append(
        emit(
            f"scalar-MUL ops/sec @ Paillier-{bits} ({SCALAR_BITS}-bit scalar)",
            tpu_ops,
            "ops/s",
            tpu_ops / cpu_ops,
            B=B,
            limbs=ctx.L,
            cpu_ops_per_sec=round(cpu_ops, 1),
            tpu_batch_ms=round(tpu_s * 1e3, 2),
            cpu_batch_ms=round(cpu_s * 1e3, 2),
        )
    )
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--k", type=int, default=16384, help="SUM fold width")
    ap.add_argument("--b", type=int, default=256, help="scalar-MUL batch")
    ap.add_argument("--sizes", default="2048,3072,4096")
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args(argv)
    out = []
    for bits in [int(s) for s in args.sizes.split(",")]:
        out += sweep_one(bits, args.k, args.b, args.repeats)
    return out


if __name__ == "__main__":
    main()
