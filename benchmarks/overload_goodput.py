"""Overload goodput: Bulwark admission control vs the 503 cliff.

The claim behind ISSUE 7: under sustained overload, a proxy WITHOUT a
decision loop lets every request burn its full Deadline budget before
503ing and lets aggregate floods starve interactive point ops; with
Bulwark (core/admission) the flood is rejected at the edge in
microseconds, so interactive goodput survives.

The harness drives ONE seeded schedule twice — admission off (baseline),
then on (bulwark) — against a fresh 4-replica deployment each time:

- a seeded ChaosNet fabric with Nemesis `delay` + periodic `flood`
  attacks (the ISSUE's "ChaosNet flood/overload schedule");
- an OPEN-LOOP arrival schedule (arrivals fire at their scheduled time
  regardless of completions — coordinated-omission-safe): an interactive
  stream of GetSet point reads plus an aggregate flood of SumAll folds at
  several times the fabric's capacity.

Reported record (`overload goodput`, parsed by benchmarks/sentry.py
--check): value = Bulwark-run interactive goodput (requests answering
200 under --good-latency-ms, per second), vs_baseline = bulwark /
baseline goodput, detail = both runs' status censuses, shed counts and
shed-latency percentiles (shed rejections must complete in MICROSECONDS,
not Deadline budgets — that is the other half of the claim).

Usage: python -m benchmarks.overload_goodput [--duration 3] [--keys 256]
       [--interactive-rate 30] [--aggregate-rate 400] [--seed 11]
"""

from __future__ import annotations

import argparse
import asyncio
import random
import time

from benchmarks.common import emit


def _percentile(xs: list[float], q: float) -> float:
    """Nearest-rank percentile; 0 for an empty sample."""
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, max(0, int(q * len(xs)) - 1))]


def _config(args, admission: bool):
    from dds_tpu.utils.config import DDSConfig

    cfg = DDSConfig()
    cfg.replicas.endpoints = [f"replica-{i}" for i in range(4)]
    cfg.replicas.sentinent = []
    cfg.replicas.byz_quorum_size = 3
    cfg.replicas.byz_max_faults = 1
    cfg.proxy.port = 0
    cfg.proxy.request_budget = args.budget
    cfg.proxy.intranet_request_timeout = args.budget / 2
    # quiet fabric: the bench measures admission, not recovery timers
    cfg.recovery.enabled = False
    cfg.recovery.anti_entropy_enabled = False
    cfg.obs.audit_enabled = False
    # short burn windows so the shedding ratchet can react within the run
    cfg.obs.slo_fast_window = 1.0
    cfg.obs.slo_slow_window = 2.0
    cfg.obs.slo_latency_ms = args.good_latency_ms
    cfg.attacks.enabled = True
    cfg.attacks.chaos_enabled = True
    cfg.attacks.chaos_seed = args.seed
    cfg.admission.enabled = admission
    cfg.admission.eval_interval = 0.2
    cfg.admission.shed_hold = 4
    # the aggregate bucket is the star: a few folds/s sustained, the rest
    # answer 429 in microseconds instead of entering the quorum machinery
    cfg.admission.aggregate_rate = args.admit_aggregate_rate
    cfg.admission.aggregate_burst = args.admit_aggregate_rate
    cfg.admission.interactive_rate = args.interactive_rate * 4
    cfg.admission.interactive_burst = args.interactive_rate * 8
    return cfg


async def _drive(args, admission: bool) -> dict:
    from dds_tpu.http.miniserver import http_request
    from dds_tpu.run import launch

    cfg = _config(args, admission)
    dep = await launch(cfg)
    rng = random.Random(args.seed)
    host, port = cfg.proxy.host, dep.server.cfg.port
    modulus = (1 << args.bits) - 159  # fixed odd fold modulus

    async def call(method, target, obj=None):
        import json as _json

        body = _json.dumps(obj).encode() if obj is not None else None
        t0 = time.perf_counter()
        try:
            status, _ = await http_request(
                host, port, method, target, body,
                timeout=args.budget + 2.0,
            )
        except (OSError, asyncio.TimeoutError, EOFError, ConnectionError):
            status = -1  # client-visible failure (timeout/reset)
        return status, time.perf_counter() - t0

    # seed the store: K single-column records of `bits`-bit "ciphertexts"
    # (random residues stand in for Paillier ciphertexts — the fold and
    # the protocol cost are identical, and the HE layer is orthogonal to
    # the admission claim)
    import json as _json

    keys = []
    for _ in range(args.keys):
        status, body = await http_request(
            host, port, "POST", "/PutSet",
            _json.dumps(
                {"contents": [str(rng.getrandbits(args.bits) % modulus)]}
            ).encode(),
            timeout=10.0,
        )
        if status != 200:
            raise RuntimeError(f"store seeding failed with {status}")
        keys.append(body.decode())

    # open-loop schedule, identical for both variants: arrival offsets are
    # drawn from the SAME seeded rng stream (uniform jitter around the
    # nominal inter-arrival gap)
    sched_rng = random.Random(args.seed + 1)

    def arrivals(rate: float) -> list[float]:
        out, t = [], 0.0
        while t < args.duration:
            out.append(t)
            t += sched_rng.uniform(0.5, 1.5) / rate
        return out

    interactive = [("interactive", t) for t in arrivals(args.interactive_rate)]
    aggregate = [("aggregate", t) for t in arrivals(args.aggregate_rate)]
    schedule = sorted(interactive + aggregate, key=lambda p: p[1])
    results: list[tuple[str, int, float]] = []

    async def fire(klass: str):
        if klass == "interactive":
            key = keys[sched_rng.randrange(len(keys))]
            status, lat = await call("GET", f"/GetSet/{key}")
        else:
            status, lat = await call(
                "GET", f"/SumAll?position=0&nsqr={modulus}"
            )
        results.append((klass, status, lat))

    async def nemesis():
        # the ChaosNet overload schedule: one delay attack up front, then
        # periodic junk floods at the replicas for the whole run
        dep.trudy.trigger("delay")
        while True:
            await asyncio.sleep(0.3)
            dep.trudy.trigger("flood")

    chaos = asyncio.ensure_future(nemesis())
    t0 = time.perf_counter()
    pending = []
    for klass, at in schedule:
        delay = at - (time.perf_counter() - t0)
        if delay > 0:
            await asyncio.sleep(delay)
        pending.append(asyncio.ensure_future(fire(klass)))
    await asyncio.wait_for(asyncio.gather(*pending), args.budget + 10.0)
    wall = time.perf_counter() - t0
    chaos.cancel()
    try:
        await chaos
    except asyncio.CancelledError:
        pass
    shed_level = dep.server.admission.shed_level if dep.server.admission else 0
    transitions = (
        len(dep.server.admission.transitions) if dep.server.admission else 0
    )
    await dep.stop()

    good_s = args.good_latency_ms / 1e3
    census: dict[str, dict[str, int]] = {}
    for klass, status, _ in results:
        c = census.setdefault(klass, {})
        label = str(status) if status > 0 else "client_error"
        c[label] = c.get(label, 0) + 1
    goodput = sum(
        1 for klass, status, lat in results
        if klass == "interactive" and status == 200 and lat <= good_s
    ) / wall
    # shed/throttled rejections (admission 429s + degraded 503s): the
    # "fail in microseconds, not budgets" half of the acceptance claim
    shed_lat = [lat for _, status, lat in results if status in (429, 503)]
    return {
        "goodput": goodput,
        "wall_s": round(wall, 3),
        "census": census,
        "shed_requests": len(shed_lat),
        "shed_p50_ms": round(_percentile(shed_lat, 0.50) * 1e3, 3),
        "shed_p95_ms": round(_percentile(shed_lat, 0.95) * 1e3, 3),
        "shed_level_final": shed_level,
        "shed_transitions": transitions,
    }


def main(argv=None) -> list:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--duration", type=float, default=3.0,
                    help="open-loop schedule length (s) per variant")
    ap.add_argument("--keys", type=int, default=256,
                    help="stored records (aggregate fold width)")
    ap.add_argument("--interactive-rate", type=float, default=30.0,
                    help="interactive GetSet arrivals/s")
    ap.add_argument("--aggregate-rate", type=float, default=400.0,
                    help="aggregate SumAll arrivals/s (the overload)")
    ap.add_argument("--admit-aggregate-rate", type=float, default=8.0,
                    help="Bulwark per-tenant aggregate bucket rate/burst")
    ap.add_argument("--budget", type=float, default=1.5,
                    help="proxy request budget (s)")
    ap.add_argument("--good-latency-ms", type=float, default=300.0,
                    help="latency bound for a request to count as goodput")
    ap.add_argument("--bits", type=int, default=4096,
                    help="stored ciphertext width")
    ap.add_argument("--seed", type=int, default=11)
    args = ap.parse_args(argv)

    baseline = asyncio.run(_drive(args, admission=False))
    bulwark = asyncio.run(_drive(args, admission=True))

    row = emit(
        "overload goodput interactive",
        bulwark["goodput"],
        "req/s",
        bulwark["goodput"] / max(baseline["goodput"], 1e-9),
        duration_s=args.duration,
        interactive_rate=args.interactive_rate,
        aggregate_rate=args.aggregate_rate,
        keys=args.keys,
        budget_s=args.budget,
        good_latency_ms=args.good_latency_ms,
        baseline_goodput=round(baseline["goodput"], 3),
        shed_requests=bulwark["shed_requests"],
        shed_p50_ms=bulwark["shed_p50_ms"],
        shed_p95_ms=bulwark["shed_p95_ms"],
        shed_transitions=bulwark["shed_transitions"],
        baseline=baseline,
        bulwark=bulwark,
    )
    return [row]


if __name__ == "__main__":
    main()
