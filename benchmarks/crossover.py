"""Fold crossover curve: device vs host per aggregate width K.

Justifies (or retunes) `TpuBackend.min_device_batch` with data instead
of a guess (r4 verdict #2): for each K it measures

- host:        native/python fold of K ciphertexts mod n^2 (the path
               small aggregates take today);
- device-lat:  ONE blocking device fold (dispatch + fetch) — what a lone
               below-crossover request would pay; on tunneled platforms
               this is floored by the link round-trip;
- device-sus:  sustained per-fold time with R pipelined dispatches —
               what concurrent serving pays per request;
- coalesced:   per-request time when R concurrent K-wide folds share one
               segmented dispatch (ops/foldmany) — the cross-request
               batching path.

The printed curve is the BASELINE.md artifact; the crossover points are
where device-lat / coalesced dip below host.

Usage: python -m benchmarks.crossover [--ks 32 64 ... ] [--r 8]
"""

from __future__ import annotations

import argparse
import time

import numpy as np

from benchmarks.common import best_of, emit, sustained_device

METRIC = "fold crossover: device vs host ms per K-wide aggregate"


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--ks", type=int, nargs="+",
                    default=[32, 64, 128, 256, 512, 1024, 2048, 4096, 8192, 16384])
    ap.add_argument("--r", type=int, default=8, help="concurrent requests")
    ap.add_argument("--host-only", action="store_true",
                    help="measure only the host fold column (no device "
                    "dispatches — usable while the TPU is unavailable; "
                    "the host side of the curve is valid either way)")
    args = ap.parse_args(argv)

    from dds_tpu import native
    from dds_tpu.bench_key import bench_paillier_key
    from dds_tpu.ops.montgomery import ModCtx

    key = bench_paillier_key()
    n2 = key.public.nsquare
    ctx = ModCtx.make(n2)
    rng = np.random.default_rng(7)

    kmax = max(args.ks)
    cs_int = [int.from_bytes(rng.bytes(ctx.L * 2), "little") % n2 for _ in range(kmax)]

    if not args.host_only:
        # device-path setup only when devices will be used: --host-only
        # must work (and stay cheap) while the TPU is unavailable
        import jax

        from dds_tpu.models.backend import TpuBackend
        from dds_tpu.ops import bignum as bn
        from dds_tpu.ops import foldmany

        be = TpuBackend(min_device_batch=0)
        kernel = be.kernel if be.pallas else "jnp"
        batch_all = bn.ints_to_batch(cs_int, ctx.L)

    rows = []
    for K in args.ks:
        cs = cs_int[:K]
        host_s = best_of(lambda: native.fold(cs, n2))

        if args.host_only:
            rows.append(
                emit(METRIC, host_s * 1e3, "ms", 0.0, K=K,
                     host_ms=round(host_s * 1e3, 3), host_only=True)
            )
            continue

        batch = np.asarray(batch_all[:K])
        dev = jax.device_put(batch)

        def one_fold():
            return np.asarray(be.reduce_mul_device(ctx, dev))

        one_fold()  # warm/compile
        lat_s = best_of(one_fold)
        sus_s = sustained_device(lambda: be.reduce_mul_device(ctx, dev), R=args.r)

        folds = [cs] * args.r
        foldmany.fold_many(folds, n2, kernel=kernel)  # warm/compile

        def coal():
            foldmany.fold_many(folds, n2, kernel=kernel)

        coal_s = best_of(coal) / args.r

        rows.append(
            emit(
                METRIC,
                host_s * 1e3,
                "ms",
                (host_s / lat_s) if lat_s else 0.0,  # >1 => device latency wins
                K=K,
                host_ms=round(host_s * 1e3, 3),
                device_latency_ms=round(lat_s * 1e3, 3),
                device_sustained_ms=round(sus_s * 1e3, 3),
                coalesced_ms_per_req=round(coal_s * 1e3, 3),
                r=args.r,
                kernel=kernel,
            )
        )

    if args.host_only:
        return rows

    # name the crossovers for BASELINE.md
    def crossover(field):
        for row in rows:
            d = row["detail"]
            if d[field] < d["host_ms"]:
                return d["K"]
        return None

    print(f"# crossover (device latency < host): K >= {crossover('device_latency_ms')}")
    print(f"# crossover (sustained < host):      K >= {crossover('device_sustained_ms')}")
    print(f"# crossover (coalesced < host):      K >= {crossover('coalesced_ms_per_req')}")
    return rows


if __name__ == "__main__":
    main()
