"""Open-loop Zipf load against a REAL multi-process Meridian fleet.

    python -m benchmarks.multihost_load [--rates 50,150] [--duration 2]

Spawns an S=2 constellation as separate OS processes on loopback TCP —
one process per quorum group (role "group:N") plus a separate proxy
(role "proxy") — waits for the proxy to report healthy, then drives the
fleet with `dds_tpu.fabric.loadgen`'s coordinated-omission-safe
open-loop generator across an arrival-rate sweep and reports p50/p95/p99
(measured from scheduled arrival instants) plus the SLO engine's burn
view. One `multihost load` record lands via `benchmarks.common.emit`;
`sentry.py --check` validates its shape.

`vs_baseline` = good completions / offered arrivals at the top rate —
1.0 means the fleet absorbed the whole open-loop offered load.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import pathlib
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FRAME_SECRET = "meridian-bench-frames"


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _toml(role: str, t_port: int, ports: dict, *, proxy_port: int = 0,
          status_port: int = 0, keys: int = 0, audit: bool = False,
          extra: str = "") -> str:
    groups = "\n".join(
        f'{gid} = "127.0.0.1:{p}"' for gid, p in sorted(ports["groups"].items())
    )
    bootstrap = ", ".join(f'"127.0.0.1:{p}"' for p in ports["status"])
    return f"""
[shard]
enabled = true
count = 2
replicas-per-group = 4
sentinent-per-group = 1
quorum-size = 3

[transport]
kind = "tcp"
host = "127.0.0.1"
port = {t_port}

[security]
transport-frame-secret = "{FRAME_SECRET}"

[recovery]
enabled = false
anti-entropy-enabled = false

[proxy]
host = "127.0.0.1"
port = {proxy_port}

[client]
nr-of-operations = {keys}

[obs]
audit-enabled = {str(audit).lower()}

[fabric]
role = "{role}"
bootstrap = [{bootstrap}]
status-port = {status_port}
gossip-wait = 5.0
admin-routes = true

[fabric.groups]
{groups}
{extra}
"""


class Fleet:
    """An S=2 loopback fleet as real OS processes: group s0, group s1,
    (optionally standby groups), and one proxy. Reused by the flagship
    multihost test, which adds a standby group and drives a live split."""

    def __init__(self, workdir: str, *, standby: int = 0,
                 proxy_count: int = 1, group_extra="",
                 proxy_extra: str = "", proxy_audit: bool = False):
        self.dir = pathlib.Path(workdir)
        gids = ["s0", "s1"] + [f"s{2 + i}" for i in range(standby)]
        self.ports = {
            "groups": {gid: free_port() for gid in gids},
            "status": [free_port() for _ in gids],
            "proxy": [free_port() for _ in range(proxy_count)],
            # proxy TRANSPORT ports are allocated up front (not at config-
            # write time) so group-process stanzas can reference them —
            # e.g. [obs.fleet] collector = the proxy's TcpNet bind
            "proxy_t": [free_port() for _ in range(proxy_count)],
        }
        self.gids = gids
        # extra TOML appended per role config; must start with a section
        # header (it lands after [fabric.groups]). group_extra may be a
        # dict gid -> stanza so one group can be armed differently (the
        # cross-host audit regression forges stale tags in s0 only)
        self.group_extra = group_extra
        self.proxy_extra = proxy_extra
        # proxy-side Watchtower audits ([obs] audit-enabled): the collector
        # feeds it stitched cross-host traces when [obs.fleet] is on too
        self.proxy_audit = proxy_audit
        self.procs: dict[str, subprocess.Popen] = {}

    def config_path(self, name: str) -> pathlib.Path:
        return self.dir / f"{name}.toml"

    @property
    def proxy_transport(self) -> str:
        """host:port of proxy0's TcpNet — the Panopticon collector addr."""
        return f"127.0.0.1:{self.ports['proxy_t'][0]}"

    def _group_extra(self, gid: str) -> str:
        if isinstance(self.group_extra, dict):
            return self.group_extra.get(gid, "")
        return self.group_extra

    def _write_configs(self) -> None:
        for i, gid in enumerate(self.gids):
            self.config_path(gid).write_text(_toml(
                f"group:{gid[1:]}", self.ports["groups"][gid], self.ports,
                status_port=self.ports["status"][i],
                extra=self._group_extra(gid),
            ))
        for i, port in enumerate(self.ports["proxy"]):
            self.config_path(f"proxy{i}").write_text(_toml(
                "proxy", self.ports["proxy_t"][i], self.ports,
                proxy_port=port, audit=self.proxy_audit,
                extra=self.proxy_extra,
            ))

    def spawn(self, name: str) -> subprocess.Popen:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = open(self.dir / f"{name}.log", "w")
        proc = subprocess.Popen(
            [sys.executable, "-m", "dds_tpu.run",
             "--config", str(self.config_path(name)), "--serve"],
            cwd=REPO, env=env, stdout=out, stderr=subprocess.STDOUT,
        )
        self.procs[name] = proc
        return proc

    def start(self) -> None:
        self._write_configs()
        for gid in self.gids:
            self.spawn(gid)
        for i in range(len(self.ports["proxy"])):
            self.spawn(f"proxy{i}")

    @property
    def proxy_targets(self) -> list[str]:
        return [f"127.0.0.1:{p}" for p in self.ports["proxy"]]

    async def wait_healthy(self, timeout: float = 90.0) -> None:
        """Poll every proxy's /health until all groups hold quorum."""
        from dds_tpu.http.miniserver import http_request

        deadline = time.monotonic() + timeout
        for port in self.ports["proxy"]:
            while True:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"fleet not healthy within {timeout}s "
                        f"(see logs under {self.dir})"
                    )
                for name, proc in self.procs.items():
                    if proc.poll() is not None:
                        raise RuntimeError(
                            f"fleet process {name} exited rc={proc.returncode} "
                            f"(see {self.dir / (name + '.log')})"
                        )
                try:
                    status, body = await http_request(
                        "127.0.0.1", port, "GET", "/health", timeout=2.0)
                    if status == 200 and json.loads(body)["status"] == "ok":
                        break
                except (OSError, asyncio.TimeoutError, ValueError,
                        EOFError, ConnectionError):
                    pass
                await asyncio.sleep(0.25)

    def stop(self) -> None:
        for proc in self.procs.values():
            if proc.poll() is None:
                proc.terminate()
        for proc in self.procs.values():
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5)
        self.procs.clear()


async def _drive(fleet: Fleet, rates: list[float], duration: float,
                 keys: int, zipf_s: float, seed: int):
    from dds_tpu.fabric.loadgen import OpenLoopLoad

    load = OpenLoopLoad(fleet.proxy_targets, keys=keys, zipf_s=zipf_s,
                        seed=seed, timeout=5.0)
    await load.seed()
    return await load.sweep(rates, duration)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rates", default="50,150",
                    help="comma-separated open-loop arrival rates (req/s)")
    ap.add_argument("--duration", type=float, default=2.0,
                    help="seconds per rate point")
    ap.add_argument("--keys", type=int, default=48)
    ap.add_argument("--zipf", type=float, default=1.1)
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args(argv)
    rates = [float(r) for r in args.rates.split(",") if r.strip()]

    from benchmarks.common import emit

    rows = []
    with tempfile.TemporaryDirectory(prefix="meridian-bench-") as workdir:
        fleet = Fleet(workdir)
        try:
            fleet.start()
            asyncio.run(fleet.wait_healthy())
            reports = asyncio.run(_drive(
                fleet, rates, args.duration, args.keys, args.zipf, args.seed
            ))
        finally:
            fleet.stop()

    top = reports[-1]
    offered = max(1, top.scheduled)
    rows.append(emit(
        "multihost load",
        top.achieved_rps,
        "req/s",
        top.good / offered,
        rates=rates,
        duration=args.duration,
        processes=len(fleet.gids) + len(fleet.ports["proxy"]),
        open_loop=True,
        zipf_s=args.zipf,
        keys=args.keys,
        p50_ms=round(top.p50_ms, 3),
        p95_ms=round(top.p95_ms, 3),
        p99_ms=round(top.p99_ms, 3),
        per_class=top.per_class,
        slo_alerts=top.slo.get("alerts", []),
        sweep=[r.to_dict() | {"slo": None} for r in reports],
    ))
    return rows


if __name__ == "__main__":
    main()
