#!/bin/bash
# Round-5 TPU measurement backlog — run when the tunneled chip is back.
# One job at a time (the tunnel is single-tenant); generous timeouts
# (first compiles 20-40 s/shape); everything appends to backlog_results/.
# Usage: bash benchmarks/tpu_backlog.sh   (from /root/repo)
set -u
cd "$(dirname "$0")/.."
OUT=benchmarks/backlog_results
mkdir -p "$OUT"

run() { # name, timeout_s, cmd...
  local name=$1 t=$2; shift 2
  echo "=== $name ==="
  timeout "$t" "$@" >"$OUT/$name.out" 2>"$OUT/$name.err"
  echo "rc=$? ($name)"
}

# 0) probe gate: refuse to start while wedged
if ! timeout 90 python -u -c "import jax; assert jax.default_backend() in ('tpu','axon'), jax.default_backend(); print('tpu ok')"; then
  echo "tunnel still wedged; aborting backlog" >&2
  exit 1
fi

# 1) fold crossover curve (device columns; justifies min_device_batch)
run crossover 1800 python -m benchmarks.crossover

# 2) encrypt-grade 2048-bit-exponent modexp + batched CRT decrypt
run encrypt_modexp 2400 python -m benchmarks.encrypt_modexp

# 3) kernel families incl. the fused Karatsuba (v1 / v2 / v2-kfused)
run kernel_compare 2400 python -m benchmarks.kernel_compare

# 4) roofline report (v2 ns/modmul vs compute floor per key size)
run profile_kernel 1800 python -m benchmarks.profile_kernel

# 5) DDS_PROD_TB sweep for the small-limb sizes (ONE PROCESS PER VALUE —
# the env is read at trace time). Covers both L=64 (RSA-1024) and L=128
# (RSA-2048), whose _tb_for defaults changed pending this measurement.
for tb in 128 256 512 1024; do
  run "product_tb$tb" 1800 env DDS_PROD_TB=$tb python -m benchmarks.product --sizes 1024,2048
done

# 6) config 5 re-spec (YCSB load phase + concurrent clients)
run mixed_respec 3600 python -m benchmarks.mixed --preload 4096 --clients 4

# 7) concurrent-client writes with the device bulk-encrypt path
run put_bulk_tpu 2400 python -m benchmarks.put_concurrency --bulk tpu --clients 1 4

# 8) the headline (also refreshes results for BENCH_rN)
run bench 3600 python bench.py

echo "backlog complete; results in $OUT/"
