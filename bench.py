"""North-star benchmark: encrypted SUM throughput @ Paillier-2048.

Measures the proxy-side homomorphic-add fold (the compute inside the
`SumAll` route, = the reference's per-ciphertext `HomoAdd.sum` loop at
`dds/http/DDSRestServer.scala:412-430`) on both crypto backends:

- cpu:  sequential python-int modmul fold mod n^2 over ciphertexts in host
        RAM (the BASELINE.md CPU reference, standing in for the JVM
        ``BigInteger`` loop)
- tpu:  one fused Pallas CIOS Montgomery tree-reduction over the proxy's
        **device-resident** ciphertext store ((K, 256) uint32 limbs in
        HBM). Residency is the architecture, not a benchmark trick: the
        proxy ingests ciphertext limbs at PutSet time and aggregates run
        on-device (the reference instead re-reads every set through full
        ABD quorums per aggregate, SURVEY.md §3.4). One-time ingest cost
        is reported in `detail`.

Both backends are verified against Paillier decryption before timing.
Timing forces a host fetch of the result (np.asarray) — on tunneled TPU
platforms `block_until_ready` can return before execution finishes.

Emits ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
value is the TPU fold's homomorphic adds/sec and vs_baseline is the
speedup over the CPU backend on this host.
"""

import json
import secrets
import time

import numpy as np


def bench(K: int = 65536, repeats: int = 3, verify: bool = True) -> dict:
    import jax

    from dds_tpu.bench_key import bench_paillier_key
    from dds_tpu.models.backend import CpuBackend, TpuBackend
    from dds_tpu.ops import bignum as bn
    from dds_tpu.ops.montgomery import ModCtx

    key = bench_paillier_key()
    pk = key.public
    n2 = pk.nsquare

    cpu = CpuBackend()
    # min_device_batch=0: the verify gate below folds 64 real ciphertexts
    # and must exercise the DEVICE path, not the adaptive host fallback
    tpu = TpuBackend(min_device_batch=0)

    if verify:
        # correctness gate on REAL ciphertexts: encrypt, fold, decrypt
        vals = [secrets.randbelow(1 << 32) for _ in range(64)]
        sub = [pk.encrypt(v) for v in vals]
        tpu_fold = tpu.modmul_fold(sub, n2)
        assert key.decrypt(tpu_fold) == sum(vals), "tpu backend SumAll decrypts wrong"
        assert tpu_fold == cpu.modmul_fold(sub, n2)

    # timing operands: uniform residues mod n^2 (statistically identical
    # modmul cost to real ciphertexts; encrypting K of them host-side would
    # dominate benchmark setup)
    cs = [secrets.randbelow(n2) for _ in range(K)]

    # CPU baseline: K-1 homomorphic adds over host-RAM ciphertexts
    t_cpu = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        cpu.modmul_fold(cs, n2)
        t_cpu.append(time.perf_counter() - t0)
    cpu_ops = (K - 1) / min(t_cpu)

    # TPU: one-time ingest into the device-resident store (paid at PutSet
    # time in the proxy), then the fold as one fused kernel chain
    ctx = ModCtx.make(n2)
    t0 = time.perf_counter()
    batch = bn.ints_to_batch(cs, ctx.L)
    resident = jax.device_put(batch)
    jax.block_until_ready(resident)
    ingest_s = time.perf_counter() - t0

    # TPU sustained throughput: benchmarks.common.sustained_device
    # pipelines R fold dispatches on the device stream and fetches ONE
    # device-side combine. A serving proxy overlaps aggregate dispatches
    # exactly like this; timing each fold with a blocking fetch would
    # measure the host<->device link's round-trip latency (~67 ms on
    # tunneled platforms), not the kernel. Per-fold latency (1 dispatch +
    # 1 blocking fetch) is reported in `detail`.
    from benchmarks.common import sustained_device

    R = 16
    np.asarray(tpu.reduce_mul_device(ctx, resident))  # warm/compile fold
    fold_s = sustained_device(
        lambda: tpu.reduce_mul_device(ctx, resident), R=R, repeats=repeats
    )
    tpu_ops = (K - 1) / fold_s

    t0 = time.perf_counter()
    np.asarray(tpu.reduce_mul_device(ctx, resident))
    lat_ms = (time.perf_counter() - t0) * 1e3

    return {
        "metric": "encrypted SUM ops/sec @ Paillier-2048 (batched homomorphic add)",
        "value": round(tpu_ops, 1),
        "unit": "ops/s",
        "vs_baseline": round(tpu_ops / cpu_ops, 3),
        "detail": {
            "K": K,
            "kernel": "pallas" if tpu.pallas else "jnp",
            "cpu_ops_per_sec": round(cpu_ops, 1),
            "tpu_fold_ms_sustained": round(fold_s * 1e3, 2),
            "tpu_fold_ms_single_dispatch": round(lat_ms, 2),
            "pipelined_folds": R,
            "cpu_fold_ms": round(min(t_cpu) * 1e3, 2),
            "ingest_ms_one_time": round(ingest_s * 1e3, 2),
        },
    }


if __name__ == "__main__":
    result = bench()
    print(json.dumps(result))
