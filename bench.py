"""North-star benchmark: encrypted SUM throughput @ Paillier-2048 under
the 4-replica (f=1) BFT quorum, END TO END (BASELINE.json's metric as
written): client-encrypted rows loaded through real quorum writes, then
timed `SumAll` requests through the REST proxy — per-request quorum
tag-validation + audit + the full homomorphic fold, decrypt-verified.
`--worker --kernel` measures the kernel-only fold (the compute inside
`SumAll`, = the reference's `HomoAdd.sum` loop at
`dds/http/DDSRestServer.scala:412-430`) on both crypto backends:

- cpu:  sequential python-int modmul fold mod n^2 over ciphertexts in host
        RAM (the BASELINE.md CPU reference, standing in for the JVM
        ``BigInteger`` loop)
- tpu:  one fused Pallas CIOS Montgomery tree-reduction over the proxy's
        **device-resident** ciphertext store ((K, 256) uint32 limbs in
        HBM). Residency is the architecture, not a benchmark trick: the
        proxy ingests ciphertext limbs at PutSet time and aggregates run
        on-device (the reference instead re-reads every set through full
        ABD quorums per aggregate, SURVEY.md §3.4). One-time ingest cost
        is reported in `detail`.

Both backends are verified against Paillier decryption before timing.

Driver-proof by construction: the default entry point is a DRIVER that
never initializes a JAX backend in-process. It probes device health in a
subprocess (with timeout + retry-with-backoff, because the tunneled TPU
platform intermittently wedges: `jax.devices()` hangs or raises
UNAVAILABLE and recovers on its own after a wait), then runs the actual
measurement in a `--worker` subprocess, and ALWAYS prints exactly one
JSON line to stdout and exits 0 — on unrecoverable failure the line is
{"metric": ..., "value": null, "error": ..., ...} with the pure-python
CPU baseline in `detail` instead of a traceback.
"""

import json
import os
import secrets
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)  # runnable as `python /path/to/bench.py` too
from benchmarks.bft_sum import METRIC  # noqa: E402 — lightweight import


# --------------------------------------------------------------------------
# worker: the real measurement (runs in a subprocess spawned by the driver)
# --------------------------------------------------------------------------

def bench(K: int = 32768, requests: int = 4, concurrency: int = 8) -> dict:
    """The north-star number AS WRITTEN in BASELINE.json: encrypted SUM
    throughput *under the 4-replica (f=1) BFT quorum*, end to end — K
    client-encrypted rows loaded through real HMAC'd quorum writes, then
    `SumAll` requests through the REST proxy (per-request tag-validation
    quorum round + audit + full homomorphic fold; decrypt-verified).
    Earlier rounds headlined the kernel-only fold here (86-102x) while the
    end-to-end figure sat at ~1x; the protocol overhead is now O(1) per
    request so the honest end-to-end number is the headline. Kernel-only
    figures remain in benchmarks/results.json + BASELINE.md."""
    from benchmarks.bft_sum import run_both

    cpu, tpu = run_both(K, requests, concurrency)
    ratio = tpu["adds_per_sec"] / cpu["adds_per_sec"]
    return {
        "metric": METRIC,
        "value": round(tpu["adds_per_sec"], 1),
        "unit": "ops/s",
        "vs_baseline": round(ratio, 3),
        "detail": {
            "K": K,
            "quorum": 3,
            "requests": requests,
            "concurrency": concurrency,
            "sustained": True,
            "end_to_end": True,
            "decrypt_verified": True,
            "cpu_adds_per_sec": round(cpu["adds_per_sec"], 1),
            "tpu_sumall_ms_seq": round(tpu["sumall_ms_seq"], 2),
            "tpu_sumall_ms_concurrent": round(tpu["sumall_ms_concurrent"], 2),
            "cpu_sumall_ms_seq": round(cpu["sumall_ms_seq"], 2),
            "tpu_phase_mean_ms": tpu["phase_mean_ms"],
            "putset_ops_per_sec": round(tpu["putset_ops_per_sec"], 1),
        },
    }


def bench_kernel(K: int = 65536, repeats: int = 3, verify: bool = True) -> dict:
    import jax
    import numpy as np

    from dds_tpu.bench_key import bench_paillier_key
    from dds_tpu.models.backend import CpuBackend, TpuBackend
    from dds_tpu.ops import bignum as bn
    from dds_tpu.ops.montgomery import ModCtx

    key = bench_paillier_key()
    pk = key.public
    n2 = pk.nsquare

    cpu = CpuBackend()
    # min_device_batch=0: the verify gate below folds 64 real ciphertexts
    # and must exercise the DEVICE path, not the adaptive host fallback
    tpu = TpuBackend(min_device_batch=0)

    if verify:
        # correctness gate on REAL ciphertexts: encrypt, fold, decrypt
        vals = [secrets.randbelow(1 << 32) for _ in range(64)]
        sub = [pk.encrypt(v) for v in vals]
        tpu_fold = tpu.modmul_fold(sub, n2)
        assert key.decrypt(tpu_fold) == sum(vals), "tpu backend SumAll decrypts wrong"
        assert tpu_fold == cpu.modmul_fold(sub, n2)

    # timing operands: uniform residues mod n^2 (statistically identical
    # modmul cost to real ciphertexts; encrypting K of them host-side would
    # dominate benchmark setup)
    cs = [secrets.randbelow(n2) for _ in range(K)]

    # CPU baseline: K-1 homomorphic adds over host-RAM ciphertexts
    t_cpu = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        cpu.modmul_fold(cs, n2)
        t_cpu.append(time.perf_counter() - t0)
    cpu_ops = (K - 1) / min(t_cpu)

    # TPU: one-time ingest into the device-resident store (paid at PutSet
    # time in the proxy), then the fold as one fused kernel chain
    ctx = ModCtx.make(n2)
    t0 = time.perf_counter()
    batch = bn.ints_to_batch(cs, ctx.L)
    resident = jax.device_put(batch)
    jax.block_until_ready(resident)
    ingest_s = time.perf_counter() - t0

    # TPU sustained throughput: benchmarks.common.sustained_device
    # pipelines R fold dispatches on the device stream and fetches ONE
    # device-side combine. A serving proxy overlaps aggregate dispatches
    # exactly like this; timing each fold with a blocking fetch would
    # measure the host<->device link's round-trip latency (~67 ms on
    # tunneled platforms), not the kernel. Per-fold latency (1 dispatch +
    # 1 blocking fetch, min over `repeats`) is reported in `detail`.
    from benchmarks.common import sustained_device

    R = 16
    np.asarray(tpu.reduce_mul_device(ctx, resident))  # warm/compile fold
    fold_s = sustained_device(
        lambda: tpu.reduce_mul_device(ctx, resident), R=R, repeats=repeats
    )
    tpu_ops = (K - 1) / fold_s

    lat_ms = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        np.asarray(tpu.reduce_mul_device(ctx, resident))
        lat_ms.append((time.perf_counter() - t0) * 1e3)

    return {
        "metric": "encrypted SUM ops/sec @ Paillier-2048 (batched homomorphic add, kernel only)",
        "value": round(tpu_ops, 1),
        "unit": "ops/s",
        "vs_baseline": round(tpu_ops / cpu_ops, 3),
        "detail": {
            "K": K,
            "kernel": "pallas" if tpu.pallas else "jnp",
            "backend": jax.default_backend(),
            "sustained": True,
            "cpu_ops_per_sec": round(cpu_ops, 1),
            "tpu_fold_ms_sustained": round(fold_s * 1e3, 2),
            "tpu_fold_ms_single_dispatch": round(min(lat_ms), 2),
            "pipelined_folds": R,
            "cpu_fold_ms": round(min(t_cpu) * 1e3, 2),
            "ingest_ms_one_time": round(ingest_s * 1e3, 2),
        },
    }


# --------------------------------------------------------------------------
# driver: probe / retry / always emit one JSON line
# --------------------------------------------------------------------------

def _log(msg: str) -> None:
    print(f"[bench-driver] {msg}", file=sys.stderr, flush=True)


def _run_sub(cmd: list[str], timeout_s: float) -> tuple[int | None, str, str]:
    """Run a subprocess from the repo root (device init hangs from other
    cwds on the tunneled platform). Returns (rc, stdout, stderr); rc=None
    means it hung past the timeout and was killed."""
    try:
        p = subprocess.run(
            cmd, cwd=REPO, capture_output=True, text=True, timeout=timeout_s
        )
        return p.returncode, p.stdout, p.stderr
    except subprocess.TimeoutExpired as e:
        out = e.stdout.decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        err = e.stderr.decode() if isinstance(e.stderr, bytes) else (e.stderr or "")
        return None, out, err


def _failure_tail(out: str, err: str, limit: int = 5) -> list[str]:
    """The informative tail of a failed subprocess: prefer error-ish lines
    (exception/UNAVAILABLE/traceback frames) over the platform warnings a
    hung probe leaves as its only stderr — BENCH_r01-r05 showed every
    failure as one clipped warning line, undebuggable from the JSON."""
    lines = [l.rstrip() for l in ((err or "") + "\n" + (out or "")).splitlines()
             if l.strip()]
    errorish = [
        l for l in lines
        if any(t in l for t in (
            "Error", "error:", "UNAVAILABLE", "Traceback", "raise ",
            "Exception", "FAILED",
        )) and not l.lstrip().startswith("WARNING")
    ]
    tail = (errorish or [l for l in lines
                         if not l.lstrip().startswith("WARNING")] or lines)
    return [l[:300] for l in tail[-limit:]]


def _classify_failure(rc: int | None, out: str, err: str) -> dict:
    """hang vs UNAVAILABLE vs crash, with the classified stderr tail."""
    text = (err or "") + "\n" + (out or "")
    if rc is None:
        kind = "hang_timeout"
    elif "UNAVAILABLE" in text:
        kind = "unavailable"
    else:
        kind = "crash"
    return {"kind": kind, "rc": rc, "tail": _failure_tail(out, err)}


def _probe_device(timeout_s: float) -> tuple[bool, str, dict]:
    """(ok, one-line summary, full classified detail)."""
    t0 = time.monotonic()
    rc, out, err = _run_sub(
        [sys.executable, "-u", "-c", "import jax; print(jax.devices())"],
        timeout_s,
    )
    elapsed = round(time.monotonic() - t0, 1)
    if rc == 0:
        last = out.strip().splitlines()[-1] if out.strip() else ""
        # rc=0 with a CPU-only device list means jax fell back to the CPU
        # backend (e.g. JAX_PLATFORMS cleared) — that is NOT a healthy TPU:
        # the worker would bank a CPU number under the TPU metric.
        if any(tag in last.lower() for tag in ("tpu", "axon")):
            return True, last, {"kind": "ok", "device": last[:200],
                                "elapsed_s": elapsed}
        return False, f"no TPU device (got {last[:120]!r})", {
            "kind": "no_tpu_device", "rc": 0, "device": last[:200],
            "elapsed_s": elapsed,
        }
    detail = _classify_failure(rc, out, err)
    detail["elapsed_s"] = elapsed
    last = detail["tail"][-1] if detail["tail"] else ""
    return False, f"{detail['kind']}: {last[:200]}", detail


def _probe_loop(
    deadline_s: float, probe_timeout_s: float, sleep_s: float
) -> tuple[bool, list[dict]]:
    """Retry the device probe until it succeeds or the deadline passes;
    returns (ok, per-attempt classified records). The tunnel's wedge
    clears on its own — waiting is the fix — and each attempt's detail
    (classification, elapsed, wait before the next try) lands in the
    emitted JSON so the perf trajectory stays debuggable from
    BENCH_*.json alone."""
    t_end = time.monotonic() + deadline_s
    attempts: list[dict] = []
    while True:
        ok, info, detail = _probe_device(probe_timeout_s)
        rec = {"attempt": len(attempts) + 1, **detail}
        attempts.append(rec)
        _log(f"probe #{rec['attempt']}: {'OK ' + info if ok else 'FAIL ' + info}")
        if ok:
            return True, attempts
        remaining = t_end - time.monotonic()
        if remaining <= 0:
            rec["wait_s"] = 0.0
            return False, attempts
        wait = min(sleep_s, max(remaining, 1.0))
        rec["wait_s"] = round(wait, 1)
        time.sleep(wait)


def _parse_worker_json(out: str) -> dict | None:
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(row, dict) and row.get("metric"):
            return row
    return None


def _cpu_fallback_detail(K: int = 65536) -> dict:
    """Pure-python CPU baseline (no jax import, cannot hang): the number
    the TPU result would have been compared against."""
    from dds_tpu.bench_key import bench_paillier_key

    n2 = bench_paillier_key().public.nsquare
    cs = [secrets.randbelow(n2) for _ in range(K)]
    t_best = None
    for _ in range(3):
        t0 = time.perf_counter()
        acc = 1
        for c in cs:
            acc = acc * c % n2
        dt = time.perf_counter() - t0
        t_best = dt if t_best is None else min(t_best, dt)
    return {
        "K": K,
        "cpu_ops_per_sec": round((K - 1) / t_best, 1),
        "cpu_fold_ms": round(t_best * 1e3, 2),
    }


def _driver() -> dict:
    probe_deadline = float(os.environ.get("DDS_BENCH_PROBE_DEADLINE", "420"))
    probe_timeout = float(os.environ.get("DDS_BENCH_PROBE_TIMEOUT", "75"))
    probe_sleep = float(os.environ.get("DDS_BENCH_PROBE_SLEEP", "45"))
    worker_timeout = float(os.environ.get("DDS_BENCH_WORKER_TIMEOUT", "1000"))
    attempts = int(os.environ.get("DDS_BENCH_ATTEMPTS", "2"))

    errors: list[str] = []
    probes: list[dict] = []   # per-driver-attempt probe attempt records
    workers: list[dict] = []  # per-driver-attempt worker failure records
    for attempt in range(1, attempts + 1):
        ok, probe_attempts = _probe_loop(
            probe_deadline, probe_timeout, probe_sleep
        )
        probes.append({"driver_attempt": attempt, "attempts": probe_attempts})
        if not ok:
            kinds = [a["kind"] for a in probe_attempts]
            errors.append(
                f"attempt {attempt}: device probe never succeeded "
                f"({len(probe_attempts)} probes: {', '.join(kinds)})"
            )
            continue
        _log(f"worker attempt {attempt} (timeout {worker_timeout:.0f}s)")
        rc, out, err = _run_sub(
            [sys.executable, "-u", os.path.join(REPO, "bench.py"), "--worker"],
            worker_timeout,
        )
        row = _parse_worker_json(out)
        if row is not None:
            # the measurement completed and was printed — keep it even if
            # the worker then died/hung in teardown (wedged tunnel threads
            # can hang interpreter exit after the work is done)
            if rc != 0:
                row.setdefault("detail", {})["worker_exit"] = (
                    "killed/timeout" if rc is None else f"rc={rc}"
                )
            return row
        wdetail = _classify_failure(rc, out, err)
        workers.append({"driver_attempt": attempt, **wdetail})
        last = wdetail["tail"][-1] if wdetail["tail"] else ""
        errors.append(
            f"attempt {attempt}: worker {wdetail['kind']}: {last[:300]}"
        )
        _log(errors[-1])

    # unrecoverable: emit the failure shape + CPU baseline, never a
    # traceback — with the FULL classified probe/worker history so the
    # perf trajectory is debuggable from the emitted JSON alone
    detail: dict = {
        "errors": errors,
        "probe": {
            "deadline_s": probe_deadline,
            "timeout_s": probe_timeout,
            "sleep_s": probe_sleep,
            "driver_attempts": probes,
        },
    }
    if workers:
        detail["workers"] = workers
    try:
        detail.update(_cpu_fallback_detail())
    except Exception as e:  # noqa: BLE001 — the JSON line must still go out
        detail["cpu_fallback_error"] = repr(e)
    return {
        "metric": METRIC,
        "value": None,
        "unit": "ops/s",
        "vs_baseline": None,
        "error": "TPU unavailable after probe/retry; see detail.errors",
        "detail": detail,
    }


def main() -> int:
    if "--worker" in sys.argv[1:]:
        fn = bench_kernel if "--kernel" in sys.argv[1:] else bench
        print(json.dumps(fn()), flush=True)
        return 0
    try:
        row = _driver()
    except Exception as e:  # noqa: BLE001 — the JSON line must still go out
        row = {
            "metric": METRIC,
            "value": None,
            "unit": "ops/s",
            "vs_baseline": None,
            "error": f"driver crashed: {e!r}",
        }
    print(json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
