"""North-star benchmark: encrypted SUM throughput @ Paillier-2048.

Measures the proxy-side homomorphic-add fold (the compute inside the
`SumAll` route, = the reference's per-ciphertext `HomoAdd.sum` loop at
`dds/http/DDSRestServer.scala:412-430`) on both crypto backends:

- cpu:  sequential python-int modmul fold mod n^2 (the BASELINE.md CPU ref)
- tpu:  one batched Montgomery tree-reduction over (K, 256) uint32 limbs

and verifies both against Paillier decryption before timing. Emits ONE
JSON line:  {"metric", "value", "unit", "vs_baseline"} where value is the
TPU backend's homomorphic adds/sec and vs_baseline is the speedup over the
CPU backend on this host.

Config matches BASELINE.json's north star: Paillier-2048 (4096-bit n^2);
the 4-replica BFT (f=1) quorum path is exercised end-to-end in
tests/test_rest.py — this bench isolates the crypto hot loop both backends
share so the number reflects kernel throughput, not HTTP overhead.
"""

import json
import secrets
import time

import numpy as np


def bench(K: int = 8192, repeats: int = 5, verify: bool = True) -> dict:
    from dds_tpu.bench_key import bench_paillier_key
    from dds_tpu.models.backend import CpuBackend, TpuBackend
    from dds_tpu.ops import bignum as bn
    from dds_tpu.ops.montgomery import ModCtx

    key = bench_paillier_key()
    pk = key.public
    n2 = pk.nsquare

    cpu = CpuBackend()
    tpu = TpuBackend()

    if verify:
        # correctness gate on REAL ciphertexts: encrypt, fold, decrypt
        vals = [secrets.randbelow(1 << 32) for _ in range(64)]
        sub = [pk.encrypt(v) for v in vals]
        tpu_fold = tpu.modmul_fold(sub, n2)
        assert key.decrypt(tpu_fold) == sum(vals), "tpu backend SumAll decrypts wrong"
        assert tpu_fold == cpu.modmul_fold(sub, n2)

    # timing operands: uniform residues mod n^2 (statistically identical
    # modmul cost to real ciphertexts; encrypting K of them host-side would
    # dominate the benchmark setup)
    cs = [secrets.randbelow(n2) for _ in range(K)]

    # CPU baseline: K-1 homomorphic adds
    t_cpu = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        cpu.modmul_fold(cs, n2)
        t_cpu.append(time.perf_counter() - t0)
    cpu_ops = (K - 1) / min(t_cpu)

    # TPU: same fold as one batched tree reduction (includes host<->device
    # transfer of the ciphertext batch, as the proxy would pay it)
    ctx = ModCtx.make(n2)
    batch = bn.ints_to_batch(cs, ctx.L)
    np.asarray(ctx.reduce_mul(batch))  # warm/compile
    t_tpu = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        np.asarray(ctx.reduce_mul(batch))
        t_tpu.append(time.perf_counter() - t0)
    tpu_ops = (K - 1) / min(t_tpu)

    return {
        "metric": "encrypted SUM ops/sec @ Paillier-2048 (batched homomorphic add)",
        "value": round(tpu_ops, 1),
        "unit": "ops/s",
        "vs_baseline": round(tpu_ops / cpu_ops, 3),
        "detail": {
            "K": K,
            "cpu_ops_per_sec": round(cpu_ops, 1),
            "tpu_fold_ms": round(min(t_tpu) * 1e3, 2),
            "cpu_fold_ms": round(min(t_cpu) * 1e3, 2),
        },
    }


if __name__ == "__main__":
    result = bench()
    print(json.dumps(result))
