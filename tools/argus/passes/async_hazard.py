"""Argus pass ``async``: hazards in the coroutine fabric.

The actor fabric runs ~139 coroutines over one event loop; a single
blocking call in any of them stalls every replica, gossip follower and
HTTP handler in the process. The rules:

- ``blocking-call`` — a known-blocking callable invoked directly inside
  an ``async def``: ``time.sleep``, ``subprocess.*``, synchronous file
  I/O (``open`` / pathlib ``read_text``-family), ``.result()`` on a
  future, ``block_until_ready``, the native bignum entry points
  (``powmod`` / ``powmod_batch`` / ``fold`` / ``modmul_fold*`` release
  the GIL but still block the calling thread for the whole modexp), and
  ``flight.record`` (a ``threading.Lock`` plus a synchronous disk write
  on the fault path — use ``flight.record_async``). Passing one of these
  as an argument (``asyncio.to_thread(fold, ...)``) is the sanctioned
  form and is not flagged.
- ``unawaited-coroutine`` — a bare expression statement calling a
  module-level ``async def`` by name, or ``self.X()`` where ``X`` is an
  async method of the enclosing class: the coroutine object is created
  and dropped, the body never runs. (Deliberately narrow — resolving
  arbitrary attribute chains cross-class is beyond an intra-procedural
  pass, and a near-miss here is worse than a miss.)
- ``dropped-task`` — ``ensure_future``/``create_task`` as a bare
  expression statement: no handle retained, so the task can be GC'd
  mid-flight and its exception is never observed.
- ``bare-task-spawn`` — any direct ``asyncio.ensure_future`` call under
  ``dds_tpu/``: the repo discipline is ``utils.tasks.supervised_task``,
  which retains the handle and logs + flight-records unexpected crashes
  (a bare spawn dies silently — the ``_key_sync_loop`` class of bug).
- ``lock-across-await`` — a synchronous ``with <lock>`` in a coroutine
  whose body awaits: every other coroutine contending for that
  ``threading.Lock`` blocks the loop until the awaited op completes.
"""

from __future__ import annotations

import ast

from tools.argus.engine import (
    Finding,
    dotted_name,
    iter_scopes,
    scope_calls,
    walked_stmts,
)

# dotted suffixes of callables that block the event loop (matched against
# the END of the call's dotted name, so `time.sleep` catches
# `time.sleep(...)` however `time` is bound)
BLOCKING_SUFFIXES = {
    "time.sleep": "blocks the loop; use asyncio.sleep",
    "subprocess.run": "blocks the loop; use asyncio.create_subprocess_exec",
    "subprocess.call": "blocks the loop; use asyncio.create_subprocess_exec",
    "subprocess.check_call": "blocks the loop; use asyncio.create_subprocess_exec",
    "subprocess.check_output": "blocks the loop; use asyncio.create_subprocess_exec",
    "os.system": "blocks the loop; use asyncio.create_subprocess_exec",
    "os.fsync": "sync disk flush (the fsync-before-rename discipline is "
                "worker-thread work); use asyncio.to_thread",
    "os.fdatasync": "sync disk flush (the fsync-before-rename discipline "
                    "is worker-thread work); use asyncio.to_thread",
    "flight.record": "threading.Lock + sync disk write on the fault path; "
                     "use flight.record_async",
}

# bare attribute names that block regardless of the owner expression
BLOCKING_ATTRS = {
    "block_until_ready": "host-side device sync; only obs/kprof.profiled "
                         "may block (run via asyncio.to_thread)",
    "read_text": "sync file I/O; use asyncio.to_thread",
    "write_text": "sync file I/O; use asyncio.to_thread",
    "read_bytes": "sync file I/O; use asyncio.to_thread",
    "write_bytes": "sync file I/O; use asyncio.to_thread",
    "result": "blocks until the future resolves; await it instead",
}

# native/batched bignum entries: GIL-releasing but thread-blocking for a
# full modexp — run them via asyncio.to_thread like server._fold does
BLOCKING_COMPUTE = {"powmod", "powmod_batch", "fold", "modmul_fold",
                    "modmul_fold_many"}

SPAWNERS = {"ensure_future", "create_task"}


def _is_lockish(expr: ast.expr) -> bool:
    name = dotted_name(expr)
    last = name.rsplit(".", 1)[-1].lower()
    return "lock" in last


class AsyncHazardPass:
    pass_id = "async"

    def applies(self, rel_path: str) -> bool:
        return rel_path.endswith(".py")

    # `bare-task-spawn` is repo discipline, not a universal hazard: only
    # dds_tpu/ is held to supervised_task (benchmarks/tests spawn freely)
    def _spawn_rule_applies(self, rel_path: str) -> bool:
        return (rel_path.startswith("dds_tpu/") or "/dds_tpu/" in rel_path
                or "fixtures/argus" in rel_path)

    def run(self, tree: ast.Module, src: str, rel_path: str) -> list[Finding]:
        out: list[Finding] = []
        module_async = {
            s.name for s in tree.body if isinstance(s, ast.AsyncFunctionDef)
        }
        class_async = self._class_async_methods(tree)
        for scope in iter_scopes(tree):
            if scope.is_async:
                out += self._blocking_calls(scope, rel_path)
                out += self._locks_across_await(scope, rel_path)
            out += self._task_rules(scope, rel_path, module_async,
                                    class_async)
        return out

    # ------------------------------------------------------------- helpers

    @staticmethod
    def _class_async_methods(tree: ast.Module) -> dict[str, set[str]]:
        """Dotted class name -> names of its async methods, for resolving
        ``self.X()`` inside a method of that class."""
        out: dict[str, set[str]] = {}

        def walk(body, prefix):
            for stmt in body:
                if isinstance(stmt, ast.ClassDef):
                    cname = f"{prefix}{stmt.name}"
                    out[cname] = {
                        s.name for s in stmt.body
                        if isinstance(s, ast.AsyncFunctionDef)
                    }
                    walk(stmt.body, cname + ".")
                elif isinstance(stmt, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    walk(stmt.body, f"{prefix}{stmt.name}.")

        walk(tree.body, "")
        return out

    def _blocking_calls(self, scope, rel_path: str) -> list[Finding]:
        out = []
        for call in scope_calls(scope.body):
            name = dotted_name(call.func)
            last = name.rsplit(".", 1)[-1]
            why = None
            for suffix, reason in BLOCKING_SUFFIXES.items():
                if name == suffix or name.endswith("." + suffix):
                    why = reason
                    break
            if why is None and isinstance(call.func, ast.Attribute):
                if last in BLOCKING_ATTRS:
                    why = BLOCKING_ATTRS[last]
            if why is None and last in BLOCKING_COMPUTE and name != "?":
                why = ("native bignum compute blocks the calling thread; "
                       "run via asyncio.to_thread")
            if why is None and isinstance(call.func, ast.Name) \
                    and call.func.id == "open":
                why = "sync file I/O; use asyncio.to_thread"
            if why is not None:
                out.append(Finding(
                    rel_path, call.lineno, self.pass_id, "blocking-call",
                    f"blocking call {name}() inside async def "
                    f"{scope.name} — {why}",
                    symbol=name, scope=scope.name,
                ))
        return out

    def _locks_across_await(self, scope, rel_path: str) -> list[Finding]:
        out = []
        for stmt in walked_stmts(scope.body):
            node = stmt
            if not isinstance(node, ast.With):
                continue
            if not any(_is_lockish(i.context_expr) for i in node.items):
                continue
            if any(isinstance(n, (ast.Await, ast.AsyncFor, ast.AsyncWith))
                   for stmt in node.body for n in ast.walk(stmt)):
                lock = next(dotted_name(i.context_expr) for i in node.items
                            if _is_lockish(i.context_expr))
                out.append(Finding(
                    rel_path, node.lineno, self.pass_id, "lock-across-await",
                    f"threading lock {lock} held across await in "
                    f"{scope.name} — every contending coroutine blocks the "
                    f"loop; use asyncio.Lock or release before awaiting",
                    symbol=lock, scope=scope.name,
                ))
        return out

    def _task_rules(self, scope, rel_path: str, module_async: set[str],
                    class_async: dict[str, set[str]]) -> list[Finding]:
        out = []
        spawn_rule = self._spawn_rule_applies(rel_path)
        # async methods of the class enclosing this scope, if any
        own_class = scope.name.rsplit(".", 1)[0] if "." in scope.name else ""
        own_async = class_async.get(own_class, set())
        for stmt in walked_stmts(scope.body):
            if not isinstance(stmt, ast.Expr) or not isinstance(
                    stmt.value, ast.Call):
                continue
            call = stmt.value
            name = dotted_name(call.func)
            last = name.rsplit(".", 1)[-1]
            unawaited = (
                (isinstance(call.func, ast.Name) and last in module_async)
                or (isinstance(call.func, ast.Attribute)
                    and isinstance(call.func.value, ast.Name)
                    and call.func.value.id == "self"
                    and last in own_async)
            )
            if last in SPAWNERS:
                out.append(Finding(
                    rel_path, call.lineno, self.pass_id, "dropped-task",
                    f"{name}() handle dropped in {scope.name} — the task "
                    f"can be GC'd mid-flight and its exception is never "
                    f"observed; use utils.tasks.supervised_task",
                    symbol=name, scope=scope.name,
                ))
            elif unawaited:
                out.append(Finding(
                    rel_path, call.lineno, self.pass_id,
                    "unawaited-coroutine",
                    f"coroutine {name}() called but never awaited in "
                    f"{scope.name} — the body never runs",
                    symbol=name, scope=scope.name,
                ))
        if spawn_rule:
            for call in scope_calls(scope.body):
                name = dotted_name(call.func)
                if name == "asyncio.ensure_future" or \
                        name.endswith(".asyncio.ensure_future"):
                    out.append(Finding(
                        rel_path, call.lineno, self.pass_id,
                        "bare-task-spawn",
                        f"direct asyncio.ensure_future in {scope.name} — "
                        f"use utils.tasks.supervised_task so the handle is "
                        f"retained and crashes are logged + flight-recorded",
                        symbol=name, scope=scope.name,
                    ))
        return out
