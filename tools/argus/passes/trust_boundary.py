"""Argus pass ``trust``: wire input must not mutate state unverified.

The dependability claim rests on HMAC-signed messages and anti-replay
nonces (PAPER.md; core/messages + utils/sigs). The bug class this pass
freezes: a handler that takes bytes off the transport and lets them
reach the replica repository, the proxy's stored-key set, or any other
long-lived state without passing a verify/nonce-burn guard first —
exactly the hole a Byzantine peer needs.

Taint seeds (the shared engine's fixpoint pass, wire profile):

- parameters named ``msg`` / ``payload`` / ``frame`` / ``wire`` /
  ``body`` of an ``async def`` (transport handlers receive exactly these),
- results of deserialization calls: ``json.loads``, ``from_wire``,
  ``from_dict``, ``M.loads``.

``match``-case captures propagate: ``case M.IWrite(key, value):`` taints
``key`` and ``value`` when the subject is tainted.

Sinks — long-lived state mutation:

- subscript stores into ``repository`` / ``*store*`` / ``incoming`` /
  ``outgoing`` attributes,
- calls to ``_store`` / ``_install_repository`` / ``install_wire``,
- ``.add(...)`` on a ``stored_keys``-ish set.

Guard: the finding only fires when the SCOPE has no verification at all
— no call whose name starts with ``validate``/``verify`` (or contains
``hmac``), no ``x.verify(...)``, and no nonce-burn membership test
against ``incoming``/``outgoing``. Scope-level (flow-insensitive) by
design: a handler that verifies *somewhere* is reviewed by humans; a
handler that never verifies is a machine-detectable hole. This is the
same conservative-in-one-direction contract as the secret pass.
"""

from __future__ import annotations

import ast

from tools.argus.engine import (
    Finding,
    dotted_name,
    iter_scopes,
    scope_calls,
    taint_scope,
)

WIRE_PARAMS = {"msg", "payload", "frame", "wire", "body"}
DESERIALIZERS = {"json.loads", "from_wire", "from_dict", "loads"}
SINK_CALLS = {"_store", "_install_repository", "install_wire"}
STATE_ATTRS = ("repository", "store", "incoming", "outgoing")


def _seed(node: ast.AST) -> str | None:
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        last = name.rsplit(".", 1)[-1]
        if name in DESERIALIZERS or last in ("from_wire", "from_dict"):
            return f"wire deserialization {name}()"
        if last == "loads" and name != "?":
            return f"wire deserialization {name}()"
    return None


def _is_state_attr(name: str) -> bool:
    last = name.rsplit(".", 1)[-1].lower()
    return any(part in last for part in STATE_ATTRS)


class TrustBoundaryPass:
    pass_id = "trust"

    def applies(self, rel_path: str) -> bool:
        return (rel_path.startswith("dds_tpu/") or "/dds_tpu/" in rel_path
                or "fixtures/argus" in rel_path)

    def run(self, tree: ast.Module, src: str, rel_path: str) -> list[Finding]:
        out: list[Finding] = []
        for scope in iter_scopes(tree):
            if scope.name == "<module>":
                continue
            taint = taint_scope(scope, _seed)
            if scope.is_async:
                for p in scope.args:
                    if p in WIRE_PARAMS and p not in taint.traces:
                        taint.seed_param(p, "wire-input")
            # re-run: parameter seeds must propagate through bindings too
            taint.run(scope.body)
            if not taint.traces:
                continue
            if self._guarded(scope):
                continue
            out += self._sink_hits(scope, taint, rel_path)
        return out

    # -------------------------------------------------------------- guards

    @staticmethod
    def _guarded(scope) -> bool:
        for call in scope_calls(scope.body):
            name = dotted_name(call.func)
            last = name.rsplit(".", 1)[-1].lower()
            if last.startswith(("validate", "verify")) or "hmac" in last:
                return True
        for stmt in ast.walk(scope.node):
            # nonce burn / replay check: `nonce in self.incoming`
            if isinstance(stmt, ast.Compare) and any(
                    isinstance(op, (ast.In, ast.NotIn)) for op in stmt.ops):
                for cmp in stmt.comparators:
                    if _is_state_attr(dotted_name(cmp)):
                        return True
        return False

    # --------------------------------------------------------------- sinks

    def _sink_hits(self, scope, taint, rel_path: str) -> list[Finding]:
        out = []
        # subscript stores into state attributes
        for stmt in ast.walk(scope.node):
            if not isinstance(stmt, ast.Assign):
                continue
            for tgt in stmt.targets:
                if not (isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.ctx, ast.Store)):
                    continue
                owner = dotted_name(tgt.value)
                if not _is_state_attr(owner):
                    continue
                tr = (taint.expr_trace(tgt.slice)
                      or taint.expr_trace(stmt.value))
                if tr is not None:
                    out.append(Finding(
                        rel_path, stmt.lineno, self.pass_id,
                        "unverified-store",
                        f"wire-derived value stored into {owner}[...] in "
                        f"{scope.name} with no verify/nonce guard in scope",
                        symbol=owner, scope=scope.name, trace=tr,
                    ))
        # sink calls
        for call in scope_calls(scope.body):
            name = dotted_name(call.func)
            last = name.rsplit(".", 1)[-1]
            is_sink = last in SINK_CALLS or (
                last == "add" and "stored_keys" in name
            )
            if not is_sink:
                continue
            args = list(call.args) + [kw.value for kw in call.keywords]
            for arg in args:
                tr = taint.expr_trace(arg)
                if tr is not None:
                    out.append(Finding(
                        rel_path, call.lineno, self.pass_id,
                        "unverified-store",
                        f"wire-derived value reaches {name}() in "
                        f"{scope.name} with no verify/nonce guard in scope",
                        symbol=name, scope=scope.name, trace=tr,
                    ))
                    break
        return out
