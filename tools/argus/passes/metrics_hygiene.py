"""Argus pass ``metrics``: hygiene of the /metrics exposition surface.

The registry (obs/metrics.py) is the fleet's shared dashboard language:
`# HELP` text is what an operator paged at 3am reads first, and label
cardinality is the difference between a scrape and an OOM. Heliograph's
canary series raised the bar — rotating exemplar labels and per-kind
enums must stay bounded by construction — so the discipline is now
machine-checked. The rules:

- ``empty-help`` — a metric call that passes ``help=""`` explicitly: the
  series renders with no `# HELP` line while LOOKING documented at the
  call site. Either write the one-line help or drop the kwarg (a later
  documented touch backfills it — see Registry._family).
- ``unbounded-label`` — a label value that interpolates request-scoped
  identity into the series space: an f-string label value with any
  formatted field, or a raw (non-literal, non-call) value bound to a
  known-unbounded label name (``tenant``, ``key``, ``trace_id``,
  ``kid``). Wire-supplied identifiers are a cardinality attack surface;
  the per-family cap folds the overflow, but every folded series is a
  blinded dashboard. Sanctioned forms pass: string/number literals, and
  any call expression (a capper like ``_cap(tenant)`` or an enum like
  ``VERDICTS.index(v)`` is a deliberate bounding step).

Scope is deliberately narrow: only calls of ``inc``/``set``/``observe``
on a registry-shaped receiver (``metrics``, ``reg``, ``registry``,
``_reg``, ``_registry`` as the final attribute) whose first argument is
a string literal metric name — so contextvar ``.set(...)`` and
``Event.set()`` never false-positive.
"""

from __future__ import annotations

import ast

from tools.argus.engine import Finding, dotted_name, iter_scopes, scope_calls

# method names on a registry receiver that create/write series
_METRIC_METHODS = {"inc", "set", "observe"}

# final attribute of the receiver's dotted name that marks it a registry
_REGISTRY_NAMES = {"metrics", "reg", "registry", "_reg", "_registry"}

# kwargs that are parameters of the call, not labels
_NON_LABEL_KWARGS = {"help", "n", "buckets"}

# label names whose values are request-scoped identity unless bounded
_UNBOUNDED_LABELS = {"tenant", "key", "trace_id", "kid"}


def _is_metric_call(call: ast.Call) -> bool:
    if not isinstance(call.func, ast.Attribute):
        return False
    if call.func.attr not in _METRIC_METHODS:
        return False
    recv = dotted_name(call.func.value)
    if recv.rsplit(".", 1)[-1] not in _REGISTRY_NAMES:
        return False
    return bool(call.args) and isinstance(call.args[0], ast.Constant) \
        and isinstance(call.args[0].value, str)


def _interpolates(value: ast.expr) -> bool:
    return isinstance(value, ast.JoinedStr) and any(
        isinstance(part, ast.FormattedValue) for part in value.values
    )


class MetricsHygienePass:
    pass_id = "metrics"

    def applies(self, rel_path: str) -> bool:
        return rel_path.endswith(".py")

    def run(self, tree: ast.Module, src: str, rel_path: str) -> list[Finding]:
        out: list[Finding] = []
        for scope in iter_scopes(tree):
            for call in scope_calls(scope.body):
                if not _is_metric_call(call):
                    continue
                metric = call.args[0].value
                out += self._check(call, metric, scope, rel_path)
        return out

    def _check(self, call: ast.Call, metric: str, scope,
               rel_path: str) -> list[Finding]:
        out = []
        for kw in call.keywords:
            if kw.arg is None:        # **labels: dynamic, another pass's war
                continue
            if kw.arg == "help":
                if isinstance(kw.value, ast.Constant) \
                        and kw.value.value == "":
                    out.append(Finding(
                        rel_path, call.lineno, self.pass_id, "empty-help",
                        f"metric {metric!r} registered with empty help text "
                        f"in {scope.name} — write the one-line # HELP or "
                        f"drop the kwarg and let a documented touch "
                        f"backfill it",
                        symbol=metric, scope=scope.name,
                    ))
                continue
            if kw.arg in _NON_LABEL_KWARGS:
                continue
            if _interpolates(kw.value):
                out.append(Finding(
                    rel_path, call.lineno, self.pass_id, "unbounded-label",
                    f"label {kw.arg}= of metric {metric!r} interpolates an "
                    f"f-string in {scope.name} — every distinct value mints "
                    f"a series; bound the value or fold it into the metric "
                    f"name",
                    symbol=metric, scope=scope.name,
                ))
            elif kw.arg in _UNBOUNDED_LABELS and not isinstance(
                    kw.value, (ast.Constant, ast.Call)):
                out.append(Finding(
                    rel_path, call.lineno, self.pass_id, "unbounded-label",
                    f"label {kw.arg}= of metric {metric!r} carries a raw "
                    f"request-scoped identifier in {scope.name} — a "
                    f"wire-supplied {kw.arg} is a cardinality attack "
                    f"surface; cap it (e.g. a bounded mapping) or baseline "
                    f"with the defense written down",
                    symbol=metric, scope=scope.name,
                ))
        return out
