"""Argus pass ``secret``: the Sanctum secret-material taint profile.

The original ``tools/secret_lint.py`` analysis (PR 10), re-expressed on
the shared engine: attribute reads of ``.p`` / ``.q`` / ``.lam`` seed
the per-scope fixpoint taint set, and any tainted value reaching a
cache-backed sink is a violation — those sinks retain (process-wide
context caches, module-level ``lru_cache``'d builders, jit executables
the persistent compile cache may serialize, the public batched-modexp
entries that memoize per-modulus Montgomery consts). Files under
``dds_tpu/sanctum/`` are exempt: that package exists to hold exactly
these computations under per-key lifetime rules.

``tools/secret_lint.py`` remains the stable CLI/API for this profile
(same exit codes, same ``Violation`` shape) and delegates here.
"""

from __future__ import annotations

import ast

from tools.argus.engine import Finding, iter_scopes, taint_scope

SECRET_ATTRS = {"p", "q", "lam"}

# sink -> why it is one (printed in the report)
SINK_REASONS = {
    "ModCtx.make": "process-wide ModCtx cache outlives every key",
    "MxuCtx.make": "process-wide MxuCtx cache outlives every key",
    "jax.jit": "jit argument may be baked into a persisted executable",
    "powmod_batch": "public batched modexp caches per-modulus consts "
                    "module-wide (use sanctum / powmod_batch_with_consts)",
    "_chunked_powmod": "routes to backend.powmod_batch (public-parameter "
                       "cache path)",
    "powmod": "dds_tpu.native.powmod memoizes per-modulus Montgomery "
              "consts module-wide (use pow() or sanctum)",
    "fold": "dds_tpu.native.fold memoizes per-modulus Montgomery consts "
            "module-wide",
}

# call-attribute names that are sinks regardless of the object they hang
# off (any CryptoBackend implements powmod_batch)
_ATTR_SINKS = {"powmod_batch"}
# bare-name call sinks (module-level functions)
_NAME_SINKS = {"_chunked_powmod", "powmod", "powmod_batch", "fold"}
# <Name>.make sinks
_MAKE_OWNERS = {"ModCtx", "MxuCtx"}

EXEMPT_PARTS = ("sanctum",)  # dds_tpu/sanctum/**: the plane itself


def _seed(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute) and node.attr in SECRET_ATTRS \
            and isinstance(node.ctx, ast.Load):
        return f"secret attribute .{node.attr}"
    return None


def _sink_name(call: ast.Call, lru_names: set[str]) -> str | None:
    f = call.func
    if isinstance(f, ast.Attribute):
        owner = None
        if isinstance(f.value, ast.Name):
            owner = f.value.id
        elif isinstance(f.value, ast.Attribute):  # mont_mxu.MxuCtx.make
            owner = f.value.attr
        if f.attr == "make" and owner in _MAKE_OWNERS:
            return f"{owner}.make"
        if f.attr == "jit" and isinstance(f.value, ast.Name) \
                and f.value.id == "jax":
            return "jax.jit"
        if f.attr in _ATTR_SINKS:
            return f.attr
        if f.attr in lru_names:
            return f.attr
        return None
    if isinstance(f, ast.Name):
        if f.id in _NAME_SINKS or f.id in lru_names:
            return f.id
    return None


def lru_cached_names(tree: ast.Module) -> set[str]:
    """Names of module-level functions decorated with functools.lru_cache
    / functools.cache (their results outlive every caller), in decorator
    AND assignment (`fn = lru_cache(...)(impl)`) form."""
    names: set[str] = set()
    for stmt in tree.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in stmt.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            label = None
            if isinstance(target, ast.Attribute):
                label = target.attr
            elif isinstance(target, ast.Name):
                label = target.id
            if label in ("lru_cache", "cache"):
                names.add(stmt.name)
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            inner = stmt.value.func
            if isinstance(inner, ast.Call):
                tgt = inner.func
                label = tgt.attr if isinstance(tgt, ast.Attribute) else (
                    tgt.id if isinstance(tgt, ast.Name) else None)
                if label in ("lru_cache", "cache"):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            names.add(t.id)
    return names


class SecretTaintPass:
    pass_id = "secret"

    def applies(self, rel_path: str) -> bool:
        parts = rel_path.replace("\\", "/").split("/")
        return not any(part in EXEMPT_PARTS for part in parts)

    def run(self, tree: ast.Module, src: str, rel_path: str) -> list[Finding]:
        lru_names = lru_cached_names(tree)
        out: list[Finding] = []
        for scope in iter_scopes(tree):
            taint = taint_scope(scope, _seed)
            from tools.argus.engine import scope_calls

            for call in scope_calls(scope.body):
                sink = _sink_name(call, lru_names)
                if sink is None:
                    continue
                args = list(call.args) + [kw.value for kw in call.keywords]
                for arg in args:
                    tr = taint.expr_trace(arg)
                    if tr is not None:
                        out.append(Finding(
                            rel_path, call.lineno, self.pass_id,
                            "secret-flow",
                            f"secret-derived value reaches {sink} — "
                            f"{SINK_REASONS.get(sink, 'cache-backed sink')}",
                            symbol=sink, scope=scope.name, trace=tr,
                        ))
                        break
        return out
