"""Argus pass ``dispatch``: jit/dispatch hygiene on the device hot path.

BENCH_r03 showed the fold path is dispatch-bound (87 ms single dispatch
vs 28 ms pipelined): the structural bugs that recreate that wall are a
``jax.jit`` object constructed per call (every call retraces — the
retrace bomb), a device→host round-trip inside a hot loop (each one
serializes the pipeline), and a stray ``block_until_ready`` outside
``obs/kprof.profiled``'s dispatch/execute split (which both stalls and
corrupts the phase accounting the perf sentry gates on). HEAAN-
demystified's thesis applies: these are detectable in the source, not
just in a profile. Rules:

- ``jit-per-call`` — ``jax.jit(...)`` inside a function scope with none
  of the repo's caching disciplines: an ``lru_cache``/``cache``/
  ``cached_property`` decorator on the builder, insertion into a
  ``*_FN_CACHE`` dict (directly or via a ``*fn_cache*`` helper), or
  assignment onto ``self`` (a per-instance compiled-fn cache, the
  Sanctum plan pattern). Module-level jit is always fine.
- ``host-roundtrip`` — ``.item()`` / ``np.asarray`` / ``np.array`` on
  the hot-path modules (ops/, resident/, parallel/, sanctum/) inside a
  ``for``/``while`` body: per-iteration host syncs serialize the device
  pipeline; hoist the transfer out of the loop or keep the value
  device-resident.
- ``stray-sync`` — ``block_until_ready`` anywhere in ``dds_tpu/``
  outside ``obs/kprof.py``: device waits belong in ``kprof.profiled``
  so dispatch and execute stay separately accounted.
"""

from __future__ import annotations

import ast

from tools.argus.engine import Finding, dotted_name, iter_scopes, scope_calls

HOT_PATH_PARTS = ("dds_tpu/ops/", "dds_tpu/resident/", "dds_tpu/parallel/",
                  "dds_tpu/sanctum/")
SYNC_EXEMPT = ("dds_tpu/obs/kprof.py",)
CACHE_DECORATORS = {"lru_cache", "cache", "cached_property"}
HOST_SYNC_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}


class DispatchHygienePass:
    pass_id = "dispatch"

    def applies(self, rel_path: str) -> bool:
        # fixture corpora are honorary hot-path files so the CLI flags
        # them when pointed at tests/fixtures/argus/ directly
        return (rel_path.startswith("dds_tpu/") or "/dds_tpu/" in rel_path
                or "fixtures/argus" in rel_path)

    def run(self, tree: ast.Module, src: str, rel_path: str) -> list[Finding]:
        out: list[Finding] = []
        for scope in iter_scopes(tree):
            if scope.name != "<module>":
                out += self._jit_per_call(scope, rel_path)
        hot = ("fixtures/argus" in rel_path
               or any(p in rel_path for p in HOT_PATH_PARTS))
        if hot:
            out += self._host_roundtrips(tree, rel_path)
        if not any(e in rel_path for e in SYNC_EXEMPT):
            out += self._stray_sync(tree, rel_path)
        return out

    # ------------------------------------------------------------ jit rule

    @staticmethod
    def _disciplined(scope) -> bool:
        """True when this function scope (or an enclosing one) follows a
        compiled-fn caching discipline."""
        sc = scope
        while sc is not None:
            if set(sc.decorators) & CACHE_DECORATORS:
                return True
            sc = sc.parent
        for node in ast.walk(scope.node):
            # fn cached into a module dict: _FN_CACHE[key] = fn, or via a
            # helper (_fn_cache_put(key, fn))
            if isinstance(node, ast.Subscript) and isinstance(
                    node.ctx, ast.Store):
                base = dotted_name(node.value).rsplit(".", 1)[-1]
                if "fn_cache" in base.lower():
                    return True
            if isinstance(node, ast.Call):
                if "fn_cache" in dotted_name(node.func).lower():
                    return True
            # per-instance cache: self._fn = jax.jit(...)
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) and isinstance(
                            tgt.value, ast.Name) and tgt.value.id == "self":
                        return True
        return False

    def _jit_per_call(self, scope, rel_path: str) -> list[Finding]:
        jit_calls = [
            c for c in scope_calls(scope.body)
            if dotted_name(c.func) in ("jax.jit", "jit")
        ]
        if not jit_calls or self._disciplined(scope):
            return []
        return [
            Finding(
                rel_path, c.lineno, self.pass_id, "jit-per-call",
                f"jax.jit constructed per call in {scope.name} — every "
                f"invocation retraces and recompiles; cache the jitted fn "
                f"(_FN_CACHE / functools.lru_cache / cached_property / an "
                f"instance attribute)",
                symbol="jax.jit", scope=scope.name,
            )
            for c in jit_calls
        ]

    # ------------------------------------------------------- host roundtrip

    def _host_roundtrips(self, tree: ast.Module, rel_path: str) -> list[Finding]:
        out = []
        for scope in iter_scopes(tree):
            loops = [
                n for stmt in scope.body for n in ast.walk(stmt)
                if isinstance(n, (ast.For, ast.While))
            ]
            for loop in loops:
                for node in ast.walk(loop):
                    if not isinstance(node, ast.Call):
                        continue
                    name = dotted_name(node.func)
                    sync = None
                    if isinstance(node.func, ast.Attribute) \
                            and node.func.attr == "item" and not node.args:
                        sync = ".item()"
                    elif name in HOST_SYNC_CALLS:
                        sync = name
                    if sync:
                        out.append(Finding(
                            rel_path, node.lineno, self.pass_id,
                            "host-roundtrip",
                            f"device→host round-trip {sync} inside a loop "
                            f"in {scope.name} — per-iteration host syncs "
                            f"serialize the pipeline; hoist the transfer "
                            f"or keep it device-resident",
                            symbol=sync, scope=scope.name,
                        ))
        return out

    # ----------------------------------------------------------- stray sync

    def _stray_sync(self, tree: ast.Module, rel_path: str) -> list[Finding]:
        out = []
        for scope in iter_scopes(tree):
            for call in scope_calls(scope.body):
                name = dotted_name(call.func)
                if name.rsplit(".", 1)[-1] == "block_until_ready":
                    out.append(Finding(
                        rel_path, call.lineno, self.pass_id, "stray-sync",
                        f"block_until_ready outside obs/kprof.profiled in "
                        f"{scope.name} — device waits belong in the "
                        f"dispatch/execute split the perf sentry gates on",
                        symbol="block_until_ready", scope=scope.name,
                    ))
        return out
