"""Argus pass registry: id -> pass factory.

A pass instance exposes:
- ``pass_id``  — the id findings/suppressions/baselines use;
- ``applies(rel_path) -> bool`` — which files it scans;
- ``run(tree, src, rel_path) -> list[Finding]``.

Adding a pass: implement the three members in a new module here,
register it in PASSES, document it in DEPLOY.md's pass catalog, and give
it a must-flag/must-pass fixture twin under tests/fixtures/argus/.
"""

from tools.argus.passes.async_hazard import AsyncHazardPass
from tools.argus.passes.dispatch import DispatchHygienePass
from tools.argus.passes.metrics_hygiene import MetricsHygienePass
from tools.argus.passes.secret_taint import SecretTaintPass
from tools.argus.passes.trust_boundary import TrustBoundaryPass

PASSES = {
    "async": AsyncHazardPass,
    "dispatch": DispatchHygienePass,
    "trust": TrustBoundaryPass,
    "secret": SecretTaintPass,
    "metrics": MetricsHygienePass,
}


def build(ids=None) -> list:
    """Instantiate the selected passes (default: all, stable order)."""
    if ids is None:
        ids = list(PASSES)
    unknown = [i for i in ids if i not in PASSES]
    if unknown:
        raise KeyError(f"unknown pass id(s): {', '.join(unknown)}")
    return [PASSES[i]() for i in ids]
