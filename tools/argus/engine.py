"""Argus engine: scopes, the fixpoint taint pass, findings, suppression.

This is the machinery ``tools/secret_lint.py`` proved out (per-scope
fixpoint taint over assignment/loop/walrus bindings), generalized so a
pass is just data: a *seed* predicate (which expressions introduce
taint), a *sink* resolver (which calls must never receive it), and an
optional *guard* predicate (scope-level sanitizers, e.g. an HMAC verify).
Non-taint rules (blocking calls in coroutines, per-call jit) use the
same scope walker and finding model.

Deliberately intra-procedural and conservative in ONE direction per
pass: a pass can miss cross-function flows (each pass's sink list closes
the known ones), but a clean report means no syntactic instance of the
bug class exists in the scanned tree — the property tier-1 freezes.

Suppression is inline and per-rule: a ``# argus: ok[pass.rule] reason``
comment on the flagged line silences exactly that rule there (``# argus:
ok`` silences every pass on the line); everything else goes through the
reviewed baseline file (tools/argus/baseline.py).
"""

from __future__ import annotations

import ast
import pathlib
import re
from dataclasses import dataclass, field

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent.parent


# ------------------------------------------------------------------ findings


@dataclass(frozen=True)
class Finding:
    """One rule violation. `snippet` (the stripped source line) rather
    than the line number keys baseline matching: pure line shifts from
    edits elsewhere do not resurface a baselined finding, but any change
    to the flagged line itself does."""

    path: str                       # repo-relative when under the repo
    line: int
    pass_id: str                    # "async" | "dispatch" | "trust" | "secret"
    rule: str                       # e.g. "blocking-call"
    message: str
    symbol: str = ""                # the call/sink the finding is about
    scope: str = ""                 # enclosing def (dotted) or "<module>"
    snippet: str = ""
    trace: tuple[str, ...] = ()     # taint propagation steps, seed first

    @property
    def key(self) -> tuple:
        return (self.path, self.pass_id, self.rule, self.scope, self.snippet)

    def to_dict(self) -> dict:
        return {
            "path": self.path, "line": self.line, "pass": self.pass_id,
            "rule": self.rule, "symbol": self.symbol, "scope": self.scope,
            "message": self.message, "snippet": self.snippet,
            "trace": list(self.trace),
        }

    def __str__(self) -> str:
        s = (f"{self.path}:{self.line}: [{self.pass_id}.{self.rule}] "
             f"{self.message}")
        if self.trace:
            s += "\n    taint: " + " -> ".join(self.trace)
        return s


# -------------------------------------------------------------------- scopes


@dataclass
class Scope:
    """One analysis scope: the module body or one (async) function body.
    Nested defs get their own Scope; statements of nested defs are NOT
    part of the enclosing scope's walk."""

    node: ast.AST                   # Module | FunctionDef | AsyncFunctionDef
    name: str                       # dotted: "Cls.meth" / "<module>"
    is_async: bool
    body: list[ast.stmt] = field(default_factory=list)
    parent: "Scope | None" = None
    decorators: tuple[str, ...] = ()

    @property
    def args(self) -> list[str]:
        a = getattr(self.node, "args", None)
        if a is None:
            return []
        names = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names


def _decorator_names(node: ast.AST) -> tuple[str, ...]:
    out = []
    for dec in getattr(node, "decorator_list", ()):
        target = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(target, ast.Attribute):
            out.append(target.attr)
        elif isinstance(target, ast.Name):
            out.append(target.id)
    return tuple(out)


def iter_scopes(tree: ast.Module):
    """Every analysis scope in the module: the module body first, then
    each function/method (async or not), depth-first, with dotted names
    through enclosing classes/functions."""
    mod = Scope(tree, "<module>", False, tree.body)
    yield mod

    def walk(body, prefix, parent):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = f"{prefix}{stmt.name}"
                sc = Scope(
                    stmt, name, isinstance(stmt, ast.AsyncFunctionDef),
                    stmt.body, parent, _decorator_names(stmt),
                )
                yield sc
                yield from walk(stmt.body, name + ".", sc)
            elif isinstance(stmt, ast.ClassDef):
                yield from walk(stmt.body, f"{prefix}{stmt.name}.", parent)
            else:
                for child in ast.iter_child_nodes(stmt):
                    if isinstance(child, ast.stmt):
                        yield from walk([child], prefix, parent)

    yield from walk(tree.body, "", mod)


def walked_stmts(body: list[ast.stmt]):
    """All statements in `body`, descending into compound statements but
    never into nested function/class definitions (those are separate
    scopes)."""
    for stmt in body:
        yield stmt
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                yield from walked_stmts([child])


def scope_calls(body: list[ast.stmt]):
    """Every Call expression reachable from `body` without entering a
    nested def (lambdas and comprehensions ARE entered — they execute in
    this scope)."""
    for stmt in walked_stmts(body):
        skip: set[int] = set()
        for node in ast.walk(stmt):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)) and node is not stmt:
                for sub in ast.walk(node):
                    skip.add(id(sub))
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) and id(node) not in skip:
                yield node


def dotted_name(node: ast.expr) -> str:
    """Best-effort dotted name of a call target / attribute chain:
    `a.b.c` -> "a.b.c"; anything non-name-ish becomes "?"."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{dotted_name(node.value)}.{node.attr}"
    if isinstance(node, ast.Call):
        return f"{dotted_name(node.func)}()"
    return "?"


def names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


# --------------------------------------------------------------- taint pass


def assign_pairs(stmt: ast.stmt):
    """(target, value) pairs for binding statements, tuple-to-tuple split
    elementwise; match-case subjects pair with every captured name."""
    pairs = []
    if isinstance(stmt, ast.Assign):
        for tgt in stmt.targets:
            pairs.append((tgt, stmt.value))
    elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        pairs.append((stmt.target, stmt.value))
    elif isinstance(stmt, ast.AugAssign):
        pairs.append((stmt.target, stmt.value))
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        pairs.append((stmt.target, stmt.iter))
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                pairs.append((item.optional_vars, item.context_expr))
    out = []
    for tgt, val in pairs:
        if (isinstance(tgt, (ast.Tuple, ast.List))
                and isinstance(val, (ast.Tuple, ast.List))
                and len(tgt.elts) == len(val.elts)):
            out.extend(zip(tgt.elts, val.elts))
        else:
            out.append((tgt, val))
    return out


def _match_captures(case: ast.match_case) -> set[str]:
    """Names bound by a match-case pattern (MatchAs/MatchStar/
    MatchMapping rest captures) — `case M.Read(key, nonce):` binds both."""
    names: set[str] = set()
    for node in ast.walk(case.pattern):
        if isinstance(node, (ast.MatchAs, ast.MatchStar)) and node.name:
            names.add(node.name)
        if isinstance(node, ast.MatchMapping) and node.rest:
            names.add(node.rest)
    return names


class Taint:
    """Per-scope fixpoint taint state: name -> propagation trace (seed
    description first, one step per binding hop)."""

    def __init__(self, seed_fn):
        # seed_fn(expr) -> str | None: a human-readable label when this
        # expression INTRODUCES taint (e.g. "read of .p")
        self.seed_fn = seed_fn
        self.traces: dict[str, tuple[str, ...]] = {}

    def expr_trace(self, node: ast.AST) -> tuple[str, ...] | None:
        """The taint trace of an expression, or None when untainted.
        Direct seeds win (shortest trace); tainted names propagate."""
        for sub in ast.walk(node):
            label = self.seed_fn(sub)
            if label:
                return (f"{label} (line {getattr(sub, 'lineno', '?')})",)
        for name in names_in(node):
            if name in self.traces:
                return self.traces[name]
        return None

    def run(self, body: list[ast.stmt]) -> "Taint":
        changed = True
        while changed:
            changed = False
            for stmt in walked_stmts(body):
                for tgt, val in assign_pairs(stmt):
                    tr = self.expr_trace(val)
                    if tr is None:
                        continue
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name) and n.id not in self.traces:
                            self.traces[n.id] = tr + (
                                f"{n.id} (line {stmt.lineno})",
                            )
                            changed = True
                if isinstance(stmt, ast.Match):
                    tr = self.expr_trace(stmt.subject)
                    if tr is not None:
                        for case in stmt.cases:
                            for name in _match_captures(case):
                                if name not in self.traces:
                                    self.traces[name] = tr + (
                                        f"{name} (case line {case.pattern.lineno})",
                                    )
                                    changed = True
                for node in ast.walk(stmt):
                    if isinstance(node, ast.NamedExpr):
                        tr = self.expr_trace(node.value)
                        if tr is not None and isinstance(node.target, ast.Name) \
                                and node.target.id not in self.traces:
                            self.traces[node.target.id] = tr + (
                                f"{node.target.id} (line {node.lineno})",
                            )
                            changed = True
        return self

    def seed_param(self, name: str, why: str) -> None:
        self.traces[name] = (f"{why} parameter {name!r}",)


def taint_scope(scope: Scope, seed_fn) -> Taint:
    return Taint(seed_fn).run(scope.body)


# --------------------------------------------------------------- suppression

_OK_RE = re.compile(r"#\s*argus:\s*ok(?:\[([a-z0-9_.,\- ]+)\])?")


def suppressions(src: str) -> dict[int, set[str] | None]:
    """line number -> suppressed rule set ("pass.rule" ids), or None for
    a blanket `# argus: ok`."""
    out: dict[int, set[str] | None] = {}
    for i, line in enumerate(src.splitlines(), start=1):
        m = _OK_RE.search(line)
        if not m:
            continue
        if m.group(1) is None:
            out[i] = None
        else:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def apply_suppressions(findings: list[Finding], src: str) -> list[Finding]:
    supp = suppressions(src)
    if not supp:
        return findings
    kept = []
    for f in findings:
        rules = supp.get(f.line, ...)
        if rules is ...:
            kept.append(f)
        elif rules is not None and f"{f.pass_id}.{f.rule}" not in rules:
            kept.append(f)
    return kept


# ------------------------------------------------------------------- linting


def rel_path(path: str | pathlib.Path) -> str:
    p = pathlib.Path(path)
    try:
        return str(p.resolve().relative_to(REPO_ROOT))
    except ValueError:
        return str(p)


def _snippet(src_lines: list[str], line: int) -> str:
    if 1 <= line <= len(src_lines):
        return src_lines[line - 1].strip()
    return ""


def lint_source(src: str, path: str, passes) -> list[Finding]:
    """Run `passes` (objects with .run(tree, scope iterator is theirs to
    build, path)) over one source text. Syntax errors become a finding of
    the synthetic `parse` pass so a broken file fails the gate loudly."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding(rel_path(path), e.lineno or 0, "parse",
                        "syntax-error", str(e))]
    src_lines = src.splitlines()
    out: list[Finding] = []
    rp = rel_path(path)
    for p in passes:
        if not p.applies(rp):
            continue
        for f in p.run(tree, src, rp):
            if not f.snippet:
                f = Finding(f.path, f.line, f.pass_id, f.rule, f.message,
                            f.symbol, f.scope, _snippet(src_lines, f.line),
                            f.trace)
            out.append(f)
    out = apply_suppressions(out, src)
    # dedupe: one (path, line, rule, symbol) regardless of walk overlap
    seen: set[tuple] = set()
    uniq = []
    for f in sorted(out, key=lambda f: (f.path, f.line, f.pass_id, f.rule)):
        k = (f.path, f.line, f.pass_id, f.rule, f.symbol)
        if k not in seen:
            seen.add(k)
            uniq.append(f)
    return uniq


def lint_file(path: str | pathlib.Path, passes) -> list[Finding]:
    p = pathlib.Path(path)
    return lint_source(p.read_text(), str(p), passes)
