"""Argus CLI: repo walking, baseline application, CI-grade exit codes.

    python -m tools.argus [paths...] [--passes async,dispatch]
                          [--baseline FILE | --no-baseline]
                          [--write-baseline] [--json] [--check]

Exit codes (the ``obs/sentry.py`` contract, shared with secret_lint):

- 0 — every scanned file clean (or every finding baselined/suppressed);
- 1 — new findings;
- 2 — malformed baseline or unknown pass id (configuration error beats
  analysis results: a gate that cannot read its exception list must not
  report "clean").

Default scan roots cover the shipped tree (``dds_tpu``, ``tools``,
``benchmarks``, the top-level entry scripts) but NOT ``tests/`` — the
must-flag fixture corpora live there and are linted explicitly by
tests/test_argus.py, each corpus asserted to flag.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from tools.argus import baseline as bl
from tools.argus.engine import REPO_ROOT, Finding, lint_file
from tools.argus.passes import PASSES, build

DEFAULT_ROOTS = ("dds_tpu", "tools", "benchmarks", "bench.py", "run.py")
SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


def iter_py_files(targets, repo_root: pathlib.Path = REPO_ROOT):
    for target in targets:
        p = pathlib.Path(target)
        if not p.is_absolute():
            p = repo_root / p
        if p.is_file() and p.suffix == ".py":
            yield p
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not any(part in SKIP_DIRS for part in f.parts):
                    yield f


def lint_paths(paths, passes) -> list[Finding]:
    out: list[Finding] = []
    for p in paths:
        out.extend(lint_file(p, passes))
    return out


def lint_repo(repo_root: str | pathlib.Path | None = None,
              pass_ids=None) -> list[Finding]:
    """All findings over the default roots (inline suppressions applied,
    baseline NOT applied — callers decide how exceptions are handled)."""
    root = pathlib.Path(repo_root) if repo_root else REPO_ROOT
    passes = build(pass_ids)
    return lint_paths(iter_py_files(DEFAULT_ROOTS, root), passes)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.argus",
        description="repo-wide static analysis: async-hazard, "
                    "dispatch-hygiene, trust-boundary, secret-taint",
    )
    ap.add_argument("paths", nargs="*", default=list(DEFAULT_ROOTS),
                    help="files/dirs to scan (default: shipped tree)")
    ap.add_argument("--passes", default=None, metavar="IDS",
                    help=f"comma-separated pass ids (default: all of "
                         f"{','.join(PASSES)})")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="baseline file (default: tools/argus/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline; report every finding")
    ap.add_argument("--write-baseline", action="store_true",
                    help="record current findings as the baseline and exit 0")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output (one JSON object)")
    ap.add_argument("--check", action="store_true",
                    help="CI mode: counts only on stdout, same exit codes")
    args = ap.parse_args(argv)

    pass_ids = None
    if args.passes:
        pass_ids = [p.strip() for p in args.passes.split(",") if p.strip()]
    try:
        passes = build(pass_ids)
    except KeyError as e:
        print(f"argus: {e.args[0]}", file=sys.stderr)
        return 2

    findings = lint_paths(iter_py_files(args.paths), passes)

    entries: list[dict] = []
    if not args.no_baseline:
        try:
            entries = bl.load_baseline(args.baseline)
        except bl.BaselineError as e:
            print(f"argus: malformed baseline: {e}", file=sys.stderr)
            return 2

    if args.write_baseline:
        n = bl.write_baseline(findings, args.baseline)
        target = args.baseline or bl.DEFAULT_BASELINE
        print(f"argus: wrote {n} entr{'y' if n == 1 else 'ies'} to {target}")
        return 0

    new, unused = bl.split_findings(findings, entries)

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in new],
            "baselined": len(findings) - len(new),
            "stale_baseline_entries": [bl.entry_key(e) for e in unused],
            "passes": [p.pass_id for p in passes],
        }, indent=2))
    elif args.check:
        print(f"argus: {len(new)} new finding(s), "
              f"{len(findings) - len(new)} baselined, "
              f"{len(unused)} stale baseline entr"
              f"{'y' if len(unused) == 1 else 'ies'}")
    else:
        for f in new:
            print(f)
        if findings and not new:
            print(f"argus: clean ({len(findings) - len(new)} baselined)")
        elif not findings:
            print("argus: clean")
        for e in unused:
            print(f"argus: stale baseline entry (code no longer flags): "
                  f"{e['path']} [{e['pass']}.{e['rule']}] {e['snippet']!r}",
                  file=sys.stderr)

    return 1 if new else 0
