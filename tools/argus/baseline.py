"""Argus baseline: reviewed, justified exceptions that persist on disk.

``tools/argus/baseline.json`` is a JSON list of entries; each matches
findings by the same key the engine uses —

    (path, pass, rule, scope, snippet)

— where ``snippet`` is the stripped source line. Matching on content
rather than line number means pure line shifts (an import added above)
do not resurface a baselined finding, but ANY edit to the flagged line
itself does, forcing a re-review. Every entry MUST carry a non-empty
``reason`` string; an entry without one — or any other shape problem —
is a *malformed baseline* and the CLI exits 2 (the ``obs/sentry.py``
contract), so a broken exception file can never silently pass the gate.
"""

from __future__ import annotations

import json
import pathlib

from tools.argus.engine import Finding

DEFAULT_BASELINE = pathlib.Path(__file__).resolve().parent / "baseline.json"

REQUIRED_KEYS = ("path", "pass", "rule", "scope", "snippet", "reason")


class BaselineError(ValueError):
    """The baseline file is malformed (CLI exit code 2)."""


def entry_key(entry: dict) -> tuple:
    return (entry["path"], entry["pass"], entry["rule"], entry["scope"],
            entry["snippet"])


def load_baseline(path: str | pathlib.Path | None = None) -> list[dict]:
    """Parse and validate the baseline. A missing file is an empty
    baseline; anything present must be fully well-formed."""
    p = pathlib.Path(path) if path is not None else DEFAULT_BASELINE
    if not p.exists():
        return []
    try:
        data = json.loads(p.read_text())
    except (OSError, json.JSONDecodeError) as e:
        raise BaselineError(f"{p}: unreadable baseline: {e}") from e
    if not isinstance(data, list):
        raise BaselineError(f"{p}: baseline must be a JSON list of entries")
    for i, entry in enumerate(data):
        if not isinstance(entry, dict):
            raise BaselineError(f"{p}: entry {i} is not an object")
        missing = [k for k in REQUIRED_KEYS if k not in entry]
        if missing:
            raise BaselineError(
                f"{p}: entry {i} missing key(s): {', '.join(missing)}")
        for k in REQUIRED_KEYS:
            if not isinstance(entry[k], str):
                raise BaselineError(f"{p}: entry {i} field {k!r} must be a "
                                    f"string")
        if not entry["reason"].strip():
            raise BaselineError(
                f"{p}: entry {i} ({entry['path']} {entry['pass']}."
                f"{entry['rule']}) has an empty reason — every baselined "
                f"finding must say why it is acceptable")
    return data


def split_findings(findings: list[Finding],
                   entries: list[dict]) -> tuple[list[Finding], list[dict]]:
    """(new_findings, unused_entries): findings with no baseline entry,
    and entries that matched nothing (stale — the code was fixed or the
    line changed, so the exception should be deleted or re-reviewed)."""
    keys = {entry_key(e) for e in entries}
    new = [f for f in findings if f.key not in keys]
    found = {f.key for f in findings}
    unused = [e for e in entries if entry_key(e) not in found]
    return new, unused


def as_entry(finding: Finding, reason: str) -> dict:
    return {
        "path": finding.path, "pass": finding.pass_id, "rule": finding.rule,
        "scope": finding.scope, "snippet": finding.snippet,
        "reason": reason,
    }


def write_baseline(findings: list[Finding],
                   path: str | pathlib.Path | None = None,
                   reason: str = "unreviewed: recorded by --write-baseline "
                                 "(replace with a real justification)") -> int:
    """Record every finding as a baseline entry. Returns the entry count.
    The placeholder reason keeps the file well-formed but is meant to be
    edited before review."""
    p = pathlib.Path(path) if path is not None else DEFAULT_BASELINE
    entries = [as_entry(f, reason) for f in findings]
    p.write_text(json.dumps(entries, indent=2, sort_keys=True) + "\n")
    return len(entries)
