"""Argus: the repo-wide static-analysis plane.

One shared scope/taint/AST engine (``tools/argus/engine.py``) runs four
passes over the tree:

- ``async``  — async-hazard: blocking calls inside coroutines, un-awaited
  coroutine calls, dropped/unsupervised task handles, threading locks
  held across ``await`` (``passes/async_hazard.py``);
- ``dispatch`` — dispatch-hygiene: per-call ``jax.jit`` construction
  outside the ``_FN_CACHE``/``lru_cache``/``cached_property`` discipline,
  device→host round-trips inside hot-path loops, stray
  ``block_until_ready`` outside ``obs/kprof.profiled``'s dispatch/execute
  split (``passes/dispatch.py``);
- ``trust`` — trust-boundary: wire-deserialized input flowing into
  store/state mutation in a scope with no HMAC-verify/nonce-burn guard
  (``passes/trust_boundary.py``);
- ``secret`` — the Sanctum secret-material taint profile that
  ``tools/secret_lint.py`` pioneered, now a pass of the shared engine
  (``passes/secret_taint.py``).

Findings carry ``file:line``, the pass id, a rule id, and (for taint
passes) the propagation trace. Intentional exceptions are either inline
(``# argus: ok[pass.rule] reason``) or entries in
``tools/argus/baseline.json`` — every entry MUST carry a reason string;
a malformed baseline is exit code 2 (the ``obs/sentry.py`` contract),
new findings are exit code 1, clean is 0.

Tier-1 entry points: ``pytest -m lint`` (tests/test_argus.py) and the
standalone CLI ``python -m tools.argus [--check] [--json]``.
"""

from tools.argus.engine import Finding, lint_file, lint_source  # noqa: F401
from tools.argus.cli import lint_repo, main  # noqa: F401
