#!/usr/bin/env python3
"""secret_lint — static audit of the Sanctum secret-material boundary.

Flags any flow of PaillierKey/RsaMultKey-derived secrets — values read
from a ``.p`` / ``.q`` / ``.lam`` attribute, and everything computed from
them — into machinery whose lifetime or residency outlives the key
(process-wide ModCtx/MxuCtx caches, module-level ``lru_cache``'d
builders, ``jax.jit`` arguments, the cached public batched-modexp entry
points). Files under ``dds_tpu/sanctum/`` are exempt — that package
exists to hold exactly these computations under per-key lifetime rules.

This tool pioneered the per-scope fixpoint taint pass; the machinery now
lives in the shared Argus engine (``tools/argus``), where the same
analysis runs as the ``secret`` pass next to the async-hazard,
dispatch-hygiene and trust-boundary passes. This module remains the
stable entry point the Sanctum tier-1 tests and docs reference: the
``Violation`` shape (with its ``.sink`` attribute), ``lint_source`` /
``lint_paths`` / ``lint_repo``, the default root set (tests/ included —
leak *fixtures* there live in strings, not code), and the exit-code
contract are unchanged. See ``tools/argus/passes/secret_taint.py`` for
the seed/sink catalog and ``python -m tools.argus`` for the full suite.

The analysis is deliberately intra-procedural and conservative in ONE
direction: it can miss cross-function flows (the sink list closes the
known ones), but a clean report means no syntactic secret flow into a
shared cache exists — the regression class this tool freezes out
(ADVICE.md round-5 medium finding; the original
``decrypt_batch(backend=...)`` pattern is the canonical fixture in
tests/test_sanctum.py).

Usage:
    python tools/secret_lint.py [path ...]     # default: repo scan
Exit status: 0 clean, 1 violations (printed one per line), 2 bad usage.
"""

from __future__ import annotations

import pathlib
import sys
from dataclasses import dataclass

if __package__ in (None, ""):
    # script mode (`python tools/secret_lint.py`): sys.path[0] is tools/,
    # so the repo root that holds the `tools` package must be added
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tools.argus.engine import lint_source as _engine_lint_source
from tools.argus.passes.secret_taint import (  # noqa: F401  (re-exports)
    EXEMPT_PARTS,
    SECRET_ATTRS,
    SINK_REASONS,
    SecretTaintPass,
)

# default scan roots, relative to the repo root (tests/ is scanned too:
# leak *fixtures* there live in strings, not code)
DEFAULT_ROOTS = ("dds_tpu", "benchmarks", "tools", "tests", "bench.py", "run.py")

# the Argus fixture corpora are deliberate violations-as-files; the repo
# gate must not trip on its own test corpus
_SKIP_MARKER = "fixtures/argus"


@dataclass(frozen=True)
class Violation:
    path: str
    line: int
    sink: str
    detail: str

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}: secret-derived value reaches "
                f"{self.sink} — {SINK_REASONS.get(self.sink, self.detail)}")


def _to_violation(finding) -> Violation:
    if finding.pass_id == "parse":
        return Violation(finding.path, finding.line, "syntax-error",
                         finding.message)
    return Violation(finding.path, finding.line, finding.symbol,
                     "secret-derived argument")


def lint_source(src: str, path: str = "<string>") -> list[Violation]:
    """Lint one python source text; returns violations (possibly empty)."""
    findings = _engine_lint_source(src, path, [SecretTaintPass()])
    return [_to_violation(f) for f in findings]


def _is_exempt(path: pathlib.Path, *, walking: bool) -> bool:
    if any(part in EXEMPT_PARTS for part in path.parts):
        return True
    # fixture corpora are only skipped during directory walks (the repo
    # gate); a file named explicitly on the CLI is always linted
    return walking and _SKIP_MARKER in str(path).replace("\\", "/")


def lint_paths(paths: list[pathlib.Path]) -> list[Violation]:
    out: list[Violation] = []
    for root in paths:
        walking = root.is_dir()
        files = sorted(root.rglob("*.py")) if walking else [root]
        for f in files:
            if _is_exempt(f.relative_to(root) if walking else f,
                          walking=walking):
                continue
            out.extend(lint_source(f.read_text(), str(f)))
    return out


def lint_repo(repo_root: pathlib.Path | None = None) -> list[Violation]:
    """Lint the default root set under `repo_root` (the tier-1 entry)."""
    root = repo_root or pathlib.Path(__file__).resolve().parent.parent
    paths = [root / r for r in DEFAULT_ROOTS]
    return lint_paths([p for p in paths if p.exists()])


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv:
        paths = [pathlib.Path(a) for a in argv]
        missing = [p for p in paths if not p.exists()]
        if missing:
            print(f"secret_lint: no such path: {missing[0]}", file=sys.stderr)
            return 2
        violations = lint_paths(paths)
    else:
        violations = lint_repo()
    for v in violations:
        print(v)
    if violations:
        print(f"secret_lint: {len(violations)} violation(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
