#!/usr/bin/env python3
"""secret_lint — static audit of the Sanctum secret-material boundary.

Flags any flow of PaillierKey/RsaMultKey-derived secrets — values read
from a ``.p`` / ``.q`` / ``.lam`` attribute, and everything computed from
them — into machinery whose lifetime or residency outlives the key:

- ``ModCtx.make(...)`` / ``MxuCtx.make(...)``: process-wide context
  caches (entries never die with a key);
- any module-level ``functools.lru_cache``'d builder defined in the same
  file (detected from its decorators);
- ``jax.jit(...)`` arguments (a jitted builder call with a secret
  argument bakes it into an executable the persistent compile cache may
  serialize);
- the public batched-modexp entry points that provably route into those
  caches in this repo: ``<backend>.powmod_batch(...)``,
  ``_chunked_powmod(...)``, and ``dds_tpu.native``'s cached ``powmod`` /
  ``powmod_batch`` / ``fold`` (their per-modulus Montgomery consts
  memoize module-wide; the consts-passing ``powmod_batch_with_consts``
  twin is the sanctioned alternative and is NOT a sink).

Files under ``dds_tpu/sanctum/`` are exempt — that package exists to
hold exactly these computations under per-key lifetime rules.

The analysis is a per-function (and per-module-body) taint pass:
attribute reads named ``p``/``q``/``lam`` seed the taint set; assignments
propagate it (tuple targets matched elementwise) to a fixpoint, so
``p2 = p * p`` and list comprehensions over tainted names are tracked.
It is deliberately intra-procedural and conservative in ONE direction:
it can miss cross-function flows (the sink list above closes the known
ones), but a clean report means no syntactic secret flow into a shared
cache exists — which is the regression class this tool exists to
freeze out (ADVICE.md round-5 medium finding; the original
``decrypt_batch(backend=...)`` pattern is the canonical fixture in
tests/test_sanctum.py).

Usage:
    python tools/secret_lint.py [path ...]     # default: repo scan
Exit status: 0 clean, 1 violations (printed one per line), 2 bad usage.
"""

from __future__ import annotations

import ast
import pathlib
import sys
from dataclasses import dataclass

SECRET_ATTRS = {"p", "q", "lam"}

# sink -> why it is one (printed in the report)
SINK_REASONS = {
    "ModCtx.make": "process-wide ModCtx cache outlives every key",
    "MxuCtx.make": "process-wide MxuCtx cache outlives every key",
    "jax.jit": "jit argument may be baked into a persisted executable",
    "powmod_batch": "public batched modexp caches per-modulus consts "
                    "module-wide (use sanctum / powmod_batch_with_consts)",
    "_chunked_powmod": "routes to backend.powmod_batch (public-parameter "
                       "cache path)",
    "powmod": "dds_tpu.native.powmod memoizes per-modulus Montgomery "
              "consts module-wide (use pow() or sanctum)",
    "fold": "dds_tpu.native.fold memoizes per-modulus Montgomery consts "
            "module-wide",
}

# call-attribute names that are sinks regardless of the object they hang
# off (any CryptoBackend implements powmod_batch)
_ATTR_SINKS = {"powmod_batch"}
# bare-name call sinks (module-level functions)
_NAME_SINKS = {"_chunked_powmod", "powmod", "powmod_batch", "fold"}
# <Name>.make sinks
_MAKE_OWNERS = {"ModCtx", "MxuCtx"}

# default scan roots, relative to the repo root (tests/ is scanned too:
# leak *fixtures* there live in strings, not code)
DEFAULT_ROOTS = ("dds_tpu", "benchmarks", "tools", "tests", "bench.py", "run.py")

EXEMPT_PARTS = ("sanctum",)  # dds_tpu/sanctum/**: the plane itself


@dataclass(frozen=True)
class Violation:
    path: str
    line: int
    sink: str
    detail: str

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}: secret-derived value reaches "
                f"{self.sink} — {SINK_REASONS.get(self.sink, self.detail)}")


def _names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _has_secret_attr(node: ast.AST) -> bool:
    return any(
        isinstance(n, ast.Attribute) and n.attr in SECRET_ATTRS
        and isinstance(n.ctx, ast.Load)
        for n in ast.walk(node)
    )


def _is_tainted(node: ast.AST, tainted: set[str]) -> bool:
    return _has_secret_attr(node) or bool(_names_in(node) & tainted)


def _assign_targets(stmt: ast.stmt):
    """(target, value) pairs for every binding statement form we track,
    with tuple-to-tuple assignments split elementwise."""
    pairs = []
    if isinstance(stmt, ast.Assign):
        for tgt in stmt.targets:
            pairs.append((tgt, stmt.value))
    elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        pairs.append((stmt.target, stmt.value))
    elif isinstance(stmt, ast.AugAssign):
        pairs.append((stmt.target, stmt.value))
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        pairs.append((stmt.target, stmt.iter))
    out = []
    for tgt, val in pairs:
        if (isinstance(tgt, (ast.Tuple, ast.List))
                and isinstance(val, (ast.Tuple, ast.List))
                and len(tgt.elts) == len(val.elts)):
            out.extend(zip(tgt.elts, val.elts))
        else:
            out.append((tgt, val))
    return out


def _walked_stmts(body: list[ast.stmt], *, into_defs: bool):
    """All statements in `body`, descending into compound statements but
    NOT into nested function/class definitions (each gets its own scope
    pass) unless into_defs."""
    for stmt in body:
        yield stmt
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)) and not into_defs:
                continue
            if isinstance(child, ast.stmt):
                yield from _walked_stmts([child], into_defs=into_defs)


def _scope_taint(body: list[ast.stmt]) -> set[str]:
    """Fixpoint taint set of local names bound (directly or transitively)
    from secret attributes within one scope."""
    tainted: set[str] = set()
    changed = True
    while changed:
        changed = False
        for stmt in _walked_stmts(body, into_defs=False):
            for tgt, val in _assign_targets(stmt):
                if not _is_tainted(val, tainted):
                    continue
                for n in ast.walk(tgt):
                    if isinstance(n, ast.Name) and n.id not in tainted:
                        tainted.add(n.id)
                        changed = True
                # walrus inside the value side
            for n in ast.walk(stmt):
                if isinstance(n, ast.NamedExpr) and _is_tainted(n.value, tainted):
                    if isinstance(n.target, ast.Name) and n.target.id not in tainted:
                        tainted.add(n.target.id)
                        changed = True
    return tainted


def _sink_name(call: ast.Call, lru_names: set[str]) -> str | None:
    f = call.func
    if isinstance(f, ast.Attribute):
        owner = None
        if isinstance(f.value, ast.Name):
            owner = f.value.id
        elif isinstance(f.value, ast.Attribute):  # mont_mxu.MxuCtx.make
            owner = f.value.attr
        if f.attr == "make" and owner in _MAKE_OWNERS:
            return f"{owner}.make"
        if f.attr == "jit" and isinstance(f.value, ast.Name) \
                and f.value.id == "jax":
            return "jax.jit"
        if f.attr in _ATTR_SINKS:
            return f.attr
        if f.attr in lru_names:
            return f.attr
        return None
    if isinstance(f, ast.Name):
        if f.id in _NAME_SINKS or f.id in lru_names:
            return f.id
        if f.id == "jit":
            return None  # bare `jit` is not imported anywhere we scan
    return None


def _lru_cached_names(tree: ast.Module) -> set[str]:
    """Names of module-level functions decorated with functools.lru_cache
    / functools.cache (their results outlive every caller)."""
    names: set[str] = set()
    for stmt in tree.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in stmt.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            label = None
            if isinstance(target, ast.Attribute):
                label = target.attr
            elif isinstance(target, ast.Name):
                label = target.id
            if label in ("lru_cache", "cache"):
                names.add(stmt.name)
    # assignment form: fn = functools.lru_cache(...)(impl)
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            inner = stmt.value.func
            if isinstance(inner, ast.Call):
                tgt = inner.func
                label = tgt.attr if isinstance(tgt, ast.Attribute) else (
                    tgt.id if isinstance(tgt, ast.Name) else None)
                if label in ("lru_cache", "cache"):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            names.add(t.id)
    return names


def _check_scope(body: list[ast.stmt], lru_names: set[str], path: str,
                 out: list[Violation]) -> None:
    tainted = _scope_taint(body)
    for stmt in _walked_stmts(body, into_defs=False):
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            sink = _sink_name(node, lru_names)
            if sink is None:
                continue
            args = list(node.args) + [kw.value for kw in node.keywords]
            for arg in args:
                if _is_tainted(arg, tainted):
                    out.append(Violation(
                        path, node.lineno, sink,
                        "secret-derived argument",
                    ))
                    break


def lint_source(src: str, path: str = "<string>") -> list[Violation]:
    """Lint one python source text; returns violations (possibly empty)."""
    tree = ast.parse(src, filename=path)
    lru_names = _lru_cached_names(tree)
    out: list[Violation] = []
    # module body, then every function/method scope independently
    _check_scope(tree.body, lru_names, path, out)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _check_scope(node.body, lru_names, path, out)
    # dedupe (a call can be reached from module + function walks)
    seen: set[tuple] = set()
    uniq = []
    for v in out:
        k = (v.path, v.line, v.sink)
        if k not in seen:
            seen.add(k)
            uniq.append(v)
    return uniq


def _is_exempt(path: pathlib.Path) -> bool:
    return any(part in EXEMPT_PARTS for part in path.parts)


def lint_paths(paths: list[pathlib.Path]) -> list[Violation]:
    out: list[Violation] = []
    for root in paths:
        files = [root] if root.is_file() else sorted(root.rglob("*.py"))
        for f in files:
            if _is_exempt(f.relative_to(root) if root.is_dir() else f):
                continue
            try:
                out.extend(lint_source(f.read_text(), str(f)))
            except SyntaxError as e:
                out.append(Violation(str(f), e.lineno or 0, "syntax-error",
                                     str(e)))
    return out


def lint_repo(repo_root: pathlib.Path | None = None) -> list[Violation]:
    """Lint the default root set under `repo_root` (the tier-1 entry)."""
    root = repo_root or pathlib.Path(__file__).resolve().parent.parent
    paths = [root / r for r in DEFAULT_ROOTS]
    return lint_paths([p for p in paths if p.exists()])


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv:
        paths = [pathlib.Path(a) for a in argv]
        missing = [p for p in paths if not p.exists()]
        if missing:
            print(f"secret_lint: no such path: {missing[0]}", file=sys.stderr)
            return 2
        violations = lint_paths(paths)
    else:
        violations = lint_repo()
    for v in violations:
        print(v)
    if violations:
        print(f"secret_lint: {len(violations)} violation(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
