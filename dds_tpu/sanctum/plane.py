"""Sanctum host plane: per-key CRT decrypt plans and the backend handle.

jax-free by design — the host-only default posture must not import the
device stack. The device leg lives in ``sanctum.device`` and is imported
lazily by ``plan_for`` only when a caller opts in.

Lifetime contract (the point of this module): every derived secret —
CRT moduli p^2/q^2, exponents p-1/q-1, Montgomery constants for them —
lives on a plan object reachable ONLY from the key that owns it. A
``weakref.finalize`` zeroizes/drops host copies when the key object is
garbage-collected; ``PaillierKey.scrub()`` does it eagerly. Nothing here
writes into ``ModCtx.make``'s shared cache, ``dds_tpu.native``'s
module-level consts cache, or any other module-level store (enforced
statically by ``tools/secret_lint.py``).
"""

from __future__ import annotations

import threading
import weakref

# host batch chunk: bounds the (rows, words) allocation per native call,
# mirroring models/paillier._chunked_powmod's sizing for the public path
_HOST_CHUNK = 8192

_PLANS_ATTR = "_sanctum_plans"
_PLANS_LOCK = threading.Lock()


class SecretBackend:
    """Policy handle for where secret-material computation runs.

    ``device=False`` (the default posture) keeps both CRT legs on the
    host; ``device=True`` is the explicit opt-in that fuses them into
    one batched device dispatch (see ``sanctum.device`` for what the
    opt-in exposes and how the persistent compile cache is bypassed).
    This is NOT a ``models.backend.CryptoBackend`` — it has no
    ``powmod_batch`` on purpose: secret moduli must never be expressible
    through the public-parameter interface again.
    """

    name = "sanctum"
    # duck-type marker PaillierKey.decrypt_batch validates: public
    # CryptoBackends don't carry it, so passing one raises loudly
    secret_plane = True

    def __init__(self, device: bool = False, chunk: int = 4096):
        self.device = bool(device)
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        self.chunk = int(chunk)


def is_secret_backend(obj) -> bool:
    """True for objects allowed to carry secret-material computation
    (the ``secret_plane`` marker — see SecretBackend)."""
    return getattr(obj, "secret_plane", False) is True


def _crt_recombine(xps, xqs, p, q, n, hp, hq, qinv):
    """The L-function + CRT recombination tail shared by the host and
    device plans: m_p = L_p(x_p) h_p, m_q = L_q(x_q) h_q, then Garner.
    Cheap host math next to the modexp legs; one body so the two plans
    cannot drift."""
    out = []
    for xp, xq in zip(xps, xqs):
        mp = (xp - 1) // p % p * hp % p
        mq = (xq - 1) // q % q * hq % q
        u = (mp - mq) * qinv % p
        out.append((mq + u * q) % n)
    return out


class HostCrtPlan:
    """Per-key batched CRT decrypt on the host.

    Precomputes once per key what the per-op path recomputed per call
    (p^2, q^2, the fixed exponents, and — when the native runtime is
    available — the Montgomery consts for both legs via
    ``native.mont_consts_uncached``, passed back in explicitly so the
    module-level consts cache never sees a secret modulus). Falls back
    to python ``pow`` without the native toolchain; results are
    bit-for-bit either way.
    """

    def __init__(self, key):
        p, q, n = key.p, key.q, key.n
        hp, hq, qinv = key._crt
        self.p, self.q, self.n = p, q, n
        self.p2, self.q2 = p * p, q * q
        self.hp, self.hq, self.qinv = hp, hq, qinv
        from dds_tpu import native

        self._consts_p = self._consts_q = None
        if native.available():
            self._consts_p = native.mont_consts_uncached(self.p2)
            self._consts_q = native.mont_consts_uncached(self.q2)
        self.closed = False

    def decrypt_batch(self, cs: list[int]) -> list[int]:
        if self.closed:
            raise RuntimeError("sanctum plan is closed (key scrubbed)")
        from dds_tpu.native import powmod_batch_with_consts

        xps: list[int] = []
        xqs: list[int] = []
        for i in range(0, len(cs), _HOST_CHUNK):
            chunk = cs[i : i + _HOST_CHUNK]
            xps.extend(powmod_batch_with_consts(
                [c % self.p2 for c in chunk], self.p - 1, self.p2,
                self._consts_p,
            ))
            xqs.extend(powmod_batch_with_consts(
                [c % self.q2 for c in chunk], self.q - 1, self.q2,
                self._consts_q,
            ))
        return _crt_recombine(
            xps, xqs, self.p, self.q, self.n, self.hp, self.hq, self.qinv
        )

    def close(self) -> None:
        """Drop the derived secrets. Python ints are immutable — there is
        nothing to overwrite in place — so 'zeroization' here means
        unlinking every reference this plan holds; the device plan
        additionally zero-fills its numpy copies."""
        self.p = self.q = self.n = self.p2 = self.q2 = 0
        self.hp = self.hq = self.qinv = 0
        self._consts_p = self._consts_q = None
        self.closed = True


def plan_for(key, backend: SecretBackend | None = None):
    """The per-key Sanctum plan for `backend`'s posture (None or
    ``device=False`` → HostCrtPlan; ``device=True`` → the fused device
    plan). Created once per (key, posture) and stored in the key's own
    ``__dict__`` — the ``_crt`` cached_property pattern, so the plan
    lives exactly as long as the key — with a ``weakref.finalize`` that
    closes (zeroizes) it when the key is collected without an explicit
    ``scrub()``."""
    want_device = backend is not None and getattr(backend, "device", False)
    plans = key.__dict__.get(_PLANS_ATTR)
    if plans is None:
        with _PLANS_LOCK:
            plans = key.__dict__.get(_PLANS_ATTR)
            if plans is None:
                plans = {}
                # frozen dataclass: write the instance dict directly,
                # exactly like functools.cached_property does
                key.__dict__[_PLANS_ATTR] = plans
    tag = "device" if want_device else "host"
    plan = plans.get(tag)
    if plan is None:
        with _PLANS_LOCK:
            plan = plans.get(tag)
            if plan is None:
                if want_device:
                    from dds_tpu.sanctum.device import SecretDevicePlan

                    plan = SecretDevicePlan(
                        key, chunk=getattr(backend, "chunk", 4096)
                    )
                else:
                    plan = HostCrtPlan(key)
                # NOTE: plan must hold no reference back to `key` (it
                # copies the ints it needs) or the finalizer could keep
                # the key alive / never fire
                weakref.finalize(key, plan.close)
                plans[tag] = plan
    return plan


def scrub_key(key) -> None:
    """Eagerly close every Sanctum plan a key accumulated and drop its
    cached CRT constants; the backing store for ``PaillierKey.scrub``."""
    with _PLANS_LOCK:
        plans = key.__dict__.pop(_PLANS_ATTR, None)
    for plan in (plans or {}).values():
        plan.close()
    key.__dict__.pop("_crt", None)
