"""Sanctum — the secret-material execution plane.

Everything that computes WITH private-key material (the CRT legs of
Paillier decryption: moduli p^2/q^2, exponents p-1/q-1) runs here, under
a memory-residency discipline the public-parameter hot path deliberately
does not have:

- per-KEY contexts and precomputed constants, stored on the key object
  itself (the ``_crt`` cached_property pattern) — never in
  ``ModCtx.make``'s process-wide cache or ``dds_tpu.native``'s
  module-level consts cache, whose entries outlive every key;
- host-only by default; an explicit device opt-in (``[crypto]
  secret-device`` / ``DDS_SECRET_DEVICE``) runs both CRT legs as one
  fused batched dispatch with every secret value passed as a traced
  ARGUMENT (nothing baked into executables) and the persistent JAX
  compilation cache bypassed for those compiles;
- explicit ``close()``/``PaillierKey.scrub()`` plus a ``weakref``
  finalizer that zeroizes host copies when the key object is dropped.

``tools/secret_lint.py`` (run as a tier-1 test) statically rejects any
new flow of key-derived values into the shared caches outside this
package. DEPLOY.md "Secret-material trust boundary (Sanctum)" is the
operator-facing contract; HEAAN-demystified (arxiv 2003.04510) and the
CRT-Paillier optimization paper (arxiv 2506.17935) are the structural
and numerical references.

This module is jax-free to import: host-only consumers (the default
posture) never pay the device stack; ``sanctum.device`` loads lazily on
first device-plan use.
"""

from dds_tpu.sanctum.plane import (
    HostCrtPlan,
    SecretBackend,
    is_secret_backend,
    plan_for,
    scrub_key,
)

__all__ = [
    "HostCrtPlan",
    "SecretBackend",
    "is_secret_backend",
    "plan_for",
    "scrub_key",
]
