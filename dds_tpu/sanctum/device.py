"""Sanctum device leg: the fused CRT-Paillier decrypt dispatch.

The CRT decrypt optimization (arxiv 2506.17935) on the batched limb
kernels, under the secret-material residency rules the public path does
not need:

- **One dispatch for both legs.** The B ciphertext residues mod p^2 and
  mod q^2 stack into a (2B, L) batch over the PER-ROW-modulus kernels
  (``ops.montgomery._mont_mul_rowmod_raw`` / ``_mont_exp_rowdigits_raw``)
  with the fixed per-key exponents p-1 / q-1 pre-decomposed into shared
  MSB-first window digits — two half-width modexps for the price of one
  batched ladder, instead of the two sequential full dispatches the old
  ``powmod_batch`` route paid.
- **No secret ever becomes a compile-time constant.** Every key-derived
  value (moduli limbs, n0inv, R^2, identity, exponent digits) is passed
  as a traced ARGUMENT, so compiled executables — in-memory and
  anywhere XLA may serialize them — contain shapes only.
- **Persistent compile cache bypassed.** Defense in depth on top of the
  above: compiles triggered inside the plane run with the persistent
  JAX compilation cache disabled (``compile_cache_bypass``), so no
  Sanctum executable is ever written to the on-disk cache that
  ``dds_tpu.__init__`` enables for the public kernels.
- **Per-plan jit, per-key lifetime.** Each plan wraps the raw kernel in
  its own ``jax.jit``; the compiled-executable cache hangs off that
  wrapper and dies with the plan (and the key). ``close()`` zero-fills
  the host numpy copies of every secret-derived array.

What the opt-in still exposes — and the host default does not — is
transient device (HBM) residency of p^2/q^2-derived values during the
dispatch; DEPLOY.md "Secret-material trust boundary (Sanctum)" spells
out that trade.
"""

from __future__ import annotations

import contextlib
import threading

import numpy as np
import jax
import jax.numpy as jnp

from dds_tpu.obs import kprof
from dds_tpu.ops import bignum as bn
from dds_tpu.ops.montgomery import (
    ModCtx,
    _exp_to_digits,
    _mont_exp_rowdigits_raw,
    _mont_mul_rowmod_raw,
)

# global (not per-plan): jax's config + cache-module state is process-wide
_BYPASS_LOCK = threading.RLock()


@contextlib.contextmanager
def compile_cache_bypass():
    """Disable the persistent JAX compilation cache around a compile.

    jax latches the cache object at first use, so flipping
    ``jax_compilation_cache_dir`` alone does NOT stop writes once any
    public kernel has compiled; the cache module must also be reset so
    it re-reads the (now empty) dir config. On exit the previous dir is
    restored and the cache reset again, so the next public compile
    re-initializes it normally.

    Process-global by nature (jax config is global): a public kernel
    compiling concurrently in another thread during the window is simply
    not persisted — it recompiles some other day. That failure mode
    loses a little warm-start time; the converse one writes secret-leg
    executables to disk. Fail-safe direction chosen accordingly.
    """
    with _BYPASS_LOCK:
        prev = jax.config.jax_compilation_cache_dir
        try:
            from jax._src import compilation_cache as _cc

            reset = _cc.reset_cache
        except Exception:  # pragma: no cover - private API drift
            reset = None
        try:
            if reset is not None:
                reset()
            jax.config.update("jax_compilation_cache_dir", None)
            yield
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)
            if reset is not None:
                try:
                    reset()
                except Exception:  # pragma: no cover
                    pass


def _fused_crt_raw(bases, N, n0inv, R2, one_mont, digits):
    """Both CRT legs in one batch: rows [0, B) are residues mod p^2,
    rows [B, 2B) residues mod q^2. N/R2/one_mont are (2, L), n0inv (2,),
    digits (E, 2) — one column of exponent digits per leg, repeated to
    per-row form here (inside the trace, so the host passes each secret
    exactly once per call)."""
    twoB, L = bases.shape
    rep = twoB // 2
    Nr = jnp.repeat(N, rep, axis=0)
    n0r = jnp.repeat(n0inv, rep)
    R2r = jnp.repeat(R2, rep, axis=0)
    oner = jnp.repeat(one_mont, rep, axis=0)
    digr = jnp.repeat(digits, rep, axis=1)        # (E, 2B)
    base_m = _mont_mul_rowmod_raw(bases, R2r, Nr, n0r)   # to Montgomery
    r = _mont_exp_rowdigits_raw(base_m, digr, oner, Nr, n0r)
    plain_one = jnp.zeros_like(bases).at[:, 0].set(1)
    return _mont_mul_rowmod_raw(r, plain_one, Nr, n0r)   # from Montgomery


class SecretModCtx:
    """Per-instance Montgomery context for a SECRET odd modulus.

    The deliberate anti-twin of ``ModCtx.make``: plain construction, no
    module-level cache, no jitted entry points of its own (the plan owns
    the jit wrapper), and ``close()`` zero-fills the host limb arrays.
    Built from ``ModCtx.build`` (the uncached constructor) so the two
    families cannot drift numerically.
    """

    def __init__(self, n: int, L: int | None = None):
        ctx = ModCtx.build(n, L)  # uncached; transient, dropped below
        self.L = ctx.L
        # own writable copies: int_to_limbs already copies, but be
        # explicit — close() overwrites these in place
        self.N = np.array(ctx.N, dtype=np.uint32)
        self.n0inv = np.uint32(ctx.n0inv)
        self.R2 = np.array(ctx.R2, dtype=np.uint32)
        self.one_mont = np.array(ctx.one_mont, dtype=np.uint32)
        self.closed = False

    def close(self) -> None:
        for arr in (self.N, self.R2, self.one_mont):
            arr.fill(0)
        self.n0inv = np.uint32(0)
        self.closed = True


class SecretDevicePlan:
    """Per-key fused CRT decrypt plan (the device opt-in).

    Holds the two ``SecretModCtx`` legs, the stacked (2, L) constant
    arrays, the pre-decomposed exponent digit matrix, and a fresh
    ``jax.jit`` wrapper around ``_fused_crt_raw``. Batches pad to the
    next power of two with base 1 (1^e = 1, discarded) so compiled
    shapes stay few even without the persistent cache.
    """

    def __init__(self, key, chunk: int = 4096):
        p, q, n = key.p, key.q, key.n
        hp, hq, qinv = key._crt
        self.p, self.q, self.n = p, q, n
        self.p2, self.q2 = p * p, q * q
        self.hp, self.hq, self.qinv = hp, hq, qinv
        self.chunk = max(1, int(chunk))
        L = max(
            bn.n_limbs_for_bits(self.p2.bit_length()),
            bn.n_limbs_for_bits(self.q2.bit_length()),
        )
        self.L = L
        self.ctx_p = SecretModCtx(self.p2, L)
        self.ctx_q = SecretModCtx(self.q2, L)
        self._N = np.stack([self.ctx_p.N, self.ctx_q.N])
        self._n0 = np.array([self.ctx_p.n0inv, self.ctx_q.n0inv], np.uint32)
        self._R2 = np.stack([self.ctx_p.R2, self.ctx_q.R2])
        self._one = np.stack([self.ctx_p.one_mont, self.ctx_q.one_mont])
        dp = _exp_to_digits(p - 1)
        dq = _exp_to_digits(q - 1)
        E = max(len(dp), len(dq))
        digits = np.zeros((E, 2), np.uint32)  # leading zeros are no-ops
        digits[E - len(dp):, 0] = dp
        digits[E - len(dq):, 1] = dq
        self._digits = digits
        self._fn = jax.jit(_fused_crt_raw)
        self.closed = False

    def decrypt_batch(self, cs: list[int]) -> list[int]:
        if self.closed:
            raise RuntimeError("sanctum plan is closed (key scrubbed)")
        out: list[int] = []
        for i in range(0, len(cs), self.chunk):
            out.extend(self._dispatch(cs[i : i + self.chunk]))
        return out

    def _dispatch(self, cs: list[int]) -> list[int]:
        B = len(cs)
        if B == 0:
            return []
        Bp = 1 << max(0, (B - 1).bit_length())
        pad = [1] * (Bp - B)
        bases = np.concatenate([
            bn.ints_to_batch([c % self.p2 for c in cs] + pad, self.L),
            bn.ints_to_batch([c % self.q2 for c in cs] + pad, self.L),
        ])
        with compile_cache_bypass():
            x = np.asarray(kprof.profiled(
                "sanctum_crt",
                lambda: self._fn(
                    jnp.asarray(bases), jnp.asarray(self._N),
                    jnp.asarray(self._n0), jnp.asarray(self._R2),
                    jnp.asarray(self._one), jnp.asarray(self._digits),
                ),
                B=B,
            ))
        xps = bn.batch_to_ints(x[:B])
        xqs = bn.batch_to_ints(x[Bp : Bp + B])
        from dds_tpu.sanctum.plane import _crt_recombine

        return _crt_recombine(
            xps, xqs, self.p, self.q, self.n, self.hp, self.hq, self.qinv
        )

    def close(self) -> None:
        for arr in (self._N, self._R2, self._one, self._digits):
            arr.fill(0)
        self._n0.fill(0)
        self.ctx_p.close()
        self.ctx_q.close()
        self._fn = None  # drops the per-plan compiled-executable cache
        self.p = self.q = self.n = self.p2 = self.q2 = 0
        self.hp = self.hq = self.qinv = 0
        self.closed = True
