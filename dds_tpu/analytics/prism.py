"""Prism: server-side encrypted analytics over the stored ciphertexts.

The store's aggregate routes fold ONE position across all records
(`SumAll`/`MultAll`); Prism generalizes that to plaintext-matrix x
Paillier-ciphertext-vector products (PC-MM, arxiv 2504.14497):

    Enc(W @ x)[r] = prod_j Enc(x_j) ** W[r][j]   mod n^2

evaluated entirely proxy-side from PUBLIC parameters — ciphertexts, the
client's plaintext weight matrix, and n^2 from the request, never keys —
the same trust boundary every other ciphertext-compute route has (and
deliberately NOT the secret-parameter path ADVICE.md flags on the decrypt
side: no CRT modulus ever enters this plane, so ModCtx's global cache and
the persistent compile cache are safe here). Negative weights ride the
n - |w| exponent encoding (`models/paillier.matvec_encode`).

This unlocks the workload class the 2017 reference never had: encrypted
scoring (`MatVec`), weighted aggregates (`WeightedSum` = one row), and
group-by rollups (`GroupBySum` = 0/1 selector rows), all without the
client downloading and decrypting the store.

Sharding: operand columns partition by owning shard group exactly like
`_fold_aggregate`'s scatter-gather, one batched weighted fold dispatches
per group CONCURRENTLY, and per-row partials merge with the mesh plane's
modular-product tail combine (`parallel/mesh.combine_partials`). Every
group shares one Paillier modulus and the row product is associative and
commutative over any column partition, so the sharded result is
bit-for-bit the unsharded one.

Request validation failures raise ValueError (mapped to 400 at the REST
edge); the row cap (`ops/flags.analytics_max_rows`) bounds how much
kernel work one request can demand.
"""

from __future__ import annotations

import asyncio
import math
import time
from dataclasses import dataclass
from typing import Callable, Optional

from dds_tpu.models.paillier import PaillierPublicKey
from dds_tpu.obs.metrics import SIZE_BUCKETS, metrics
from dds_tpu.utils.trace import tracer


@dataclass
class Prism:
    """The analytics engine one REST proxy owns: a ciphertext backend, the
    per-request row cap, (when sharded) the key -> group-id resolver the
    scatter partition uses (None = unsharded, single dispatch), and
    (when Lodestone is armed) the resident plane whose per-group pools
    the MatVec operand columns gather from — device-resident rows replace
    the per-request host int -> limb marshaling on the device path."""

    backend: object
    max_rows: int = 256
    owner: Optional[Callable[[str], str]] = None
    resident: object = None

    # ------------------------------------------------------------ validation

    @staticmethod
    def parse_nsqr(nsqr: str) -> tuple[int, int]:
        """(n, n^2) from the route's decimal `nsqr` query param. The weight
        encoding needs n itself, which must exist: a non-square `nsqr`
        cannot be a Paillier modulus and is rejected as a bad request."""
        try:
            n2 = int(nsqr)
        except ValueError:
            raise ValueError("nsqr must be a decimal integer") from None
        n = math.isqrt(n2) if n2 > 0 else 0
        if n < 3 or n * n != n2:
            raise ValueError("nsqr must be a perfect square (Paillier n^2)")
        return n, n2

    def encode_weights(
        self, rows: list[list[int]], n: int, cols: int
    ) -> list[list[int]]:
        """Shape-check a signed weight matrix against the operand count and
        encode it to exponent residues (negatives -> n - |w|)."""
        if not rows:
            raise ValueError("weights must have at least one row")
        if len(rows) > self.max_rows:
            raise ValueError(
                f"{len(rows)} weight rows exceed the analytics row cap "
                f"{self.max_rows} (DDS_ANALYTICS_MAX_ROWS / [analytics] "
                f"max-rows)"
            )
        for row in rows:
            if len(row) != cols:
                raise ValueError(
                    f"weight rows must span the {cols} stored operand "
                    f"column(s) at this position, got {len(row)}"
                )
        return PaillierPublicKey(n).matvec_encode(rows)

    def selector_rows(
        self, groups: dict[str, list[str]], keys: list[str]
    ) -> tuple[list[str], list[list[int]]]:
        """GroupBySum's 0/1 weight matrix: one selector row per group
        label (sorted, for a deterministic response), 1 where the operand
        column's record key is in the group. A group naming a key that is
        not an operand column is a bad request — silently dropping it
        would return a rollup over a different set than asked for."""
        if not groups:
            raise ValueError("groups must name at least one group")
        if len(groups) > self.max_rows:
            raise ValueError(
                f"{len(groups)} groups exceed the analytics row cap "
                f"{self.max_rows}"
            )
        index = {k: i for i, k in enumerate(keys)}
        labels = sorted(groups)
        rows = []
        for label in labels:
            row = [0] * len(keys)
            for k in groups[label]:
                i = index.get(k)
                if i is None:
                    raise ValueError(
                        f"group {label!r} names unknown record key {k!r}"
                    )
                row[i] = 1
            rows.append(row)
        return labels, rows

    # ------------------------------------------------------------ evaluation

    def _partition(self, keys: list[str]) -> list[tuple[str, list[int]]]:
        """Column indices grouped by owning shard group id; unsharded =
        one anonymous group (a single dispatch either way when only one
        part comes back)."""
        if self.owner is None:
            return [("", list(range(len(keys))))]
        groups: dict[str, list[int]] = {}
        for i, k in enumerate(keys):
            groups.setdefault(self.owner(k), []).append(i)
        return list(groups.items())

    def _gather(self, gid: str, sub_cs: list[int], rows: int, n2: int,
                tenant: str = ""):
        """Resident device rows for one group's operand columns, or None
        when residency does not apply: no plane, a host backend (it works
        from the ints), a below-crossover request (the host loop wins),
        or a set wider than its pool. Residency is an optimization only —
        None always degrades to the marshaling path. `tenant` names the
        Bastion pool stripe the rows gather from ("" = the anonymous
        single-tenant stripe)."""
        mdb = getattr(self.backend, "min_device_batch", None)
        if self.resident is None or mdb is None:
            return None
        if rows * len(sub_cs) < mdb:
            return None
        return self.resident.rows_for(gid, n2, sub_cs, tenant)

    async def evaluate(
        self,
        route: str,
        keys: list[str],
        ciphers: list[int],
        encoded: list[list[int]],
        n2: int,
        tenant: str = "",
    ) -> list[int]:
        """Dispatch one request's encoded weighted fold: scatter per shard
        when the columns span groups, gather with combine_partials."""
        R, K = len(encoded), len(ciphers)
        metrics.inc(
            "dds_analytics_requests_total", route=route,
            help="Prism encrypted-analytics requests by route",
        )
        metrics.observe(
            "dds_analytics_rows", R, buckets=SIZE_BUCKETS,
            help="weight rows per analytics request",
        )
        metrics.observe(
            "dds_analytics_cols", K, buckets=SIZE_BUCKETS,
            help="ciphertext operand columns per analytics request",
        )
        parts = self._partition(keys)
        t0 = time.perf_counter()
        backend_name = getattr(self.backend, "name", "?")
        with tracer.span(
            "analytics.matvec", rows=R, cols=K,
            shards=len(parts), backend=backend_name,
        ):
            if len(parts) > 1:
                # one weighted fold per owning group, dispatched
                # concurrently (each on a worker thread so device/host
                # folds overlap), merged per row with the same tail
                # combine the SumAll scatter path uses; operands gather
                # from each group's resident pool when Lodestone is armed
                from dds_tpu.parallel.mesh import combine_partials

                async def one(gid: str, idxs: list[int]) -> list[int]:
                    sub_cs = [ciphers[i] for i in idxs]
                    sub_w = [[row[i] for i in idxs] for row in encoded]
                    rows = self._gather(gid, sub_cs, R, n2, tenant)
                    return await asyncio.to_thread(
                        self.backend.matvec, sub_cs, sub_w, n2, rows
                    )

                partials = await asyncio.gather(
                    *(one(gid, ix) for gid, ix in parts)
                )
                out = [
                    combine_partials([p[r] for p in partials], n2)
                    for r in range(R)
                ]
            else:
                gid = parts[0][0] if parts else ""
                rows = self._gather(gid, ciphers, R, n2, tenant)
                out = await asyncio.to_thread(
                    self.backend.matvec, ciphers, encoded, n2, rows
                )
        metrics.observe(
            "dds_analytics_matvec_seconds", time.perf_counter() - t0,
            help="analytics weighted-fold evaluation latency",
        )
        return out
