"""Prism: the encrypted analytics plane (plaintext-matrix x
ciphertext-vector products over Paillier, served as sharded REST routes).
See prism.py for the engine and DEPLOY.md "Encrypted analytics"."""

from dds_tpu.analytics.prism import Prism  # noqa: F401
