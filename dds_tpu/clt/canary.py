"""Heliograph's golden transactions: the client half of the canary plane.

A `CanaryClient` owns a private crypto domain (its own small-key
`HomoProvider` — the prober measures the PIPE, not the modmul kernel) and
a known plaintext population of canonical 8-column rows stored under the
reserved `__heliograph__` tenant. Every probe drives a REAL route through
the REST edge — the same HTTP parser, tenant clamp, admission carve-out,
quorum client, and fold/search/analytics planes user traffic takes — and
then verifies the answer *by decrypting it*:

- ``putget``: PutSet one population row (content-addressed: the returned
  key must equal the known key) -> GetSet read-your-write -> decrypt_row
  and compare column-for-column against the known plaintext;
- ``sum`` / ``mult``: SumAll over the PSSE column / MultAll over the MSE
  column -> decrypt and compare against the population's known sum /
  product — a wrong-but-well-MAC'd ciphertext fails HERE, where no
  passive integrity check can see it;
- ``search``: one Spyglass SearchEq on the deterministic CHE column ->
  the matching canary key (and only it) must come back;
- ``matvec``: one Prism MatVec with a known weight matrix -> decrypt each
  output and compare against the dot products recomputed over the
  response's own key order.

The exact-value checks are sound because canary visibility is
ownership-scoped at the server (http/server.py `_tenant_pairs`): a canary
aggregate folds exactly the canary population, with or without Bastion
tenancy enabled — and, symmetrically, canary rows never appear in any
user-facing aggregate, search, or analytics result.

Probes return a `ProbeCheck`; classification into ok / slow / wrong-answer
/ unreachable (deadlines, latency thresholds, scheduling) lives in
obs/heliograph.py. Network-level failures propagate as exceptions — the
prober's deadline wrapper turns them into `unreachable` verdicts.
"""

from __future__ import annotations

import asyncio
import json
import secrets
from dataclasses import dataclass, field

from dds_tpu.core.tenant import CANARY_TENANT
from dds_tpu.http.miniserver import http_request
from dds_tpu.models._symmetric import aes_available
from dds_tpu.models.facade import DEFAULT_SCHEMA, HomoProvider
from dds_tpu.obs import context as obs_context

__all__ = [
    "CanaryTarget", "ProbeCheck", "CanaryClient", "PROBE_KINDS",
    "parse_canary_targets",
]

PROBE_KINDS = ("putget", "sum", "mult", "search", "matvec")

# canonical column positions in DEFAULT_SCHEMA
_OPE_POS, _CHE_POS, _PSSE_POS, _MSE_POS = 0, 1, 2, 3
_FIXED_COLUMNS = 8


@dataclass(frozen=True)
class CanaryTarget:
    """One proxy edge the prober drives golden transactions against."""

    host: str
    port: int
    region: str = ""

    @property
    def label(self) -> str:
        return f"{self.host}:{self.port}"


def parse_canary_targets(entries) -> tuple[list[CanaryTarget], list[str]]:
    """Configured `[heliograph].targets` entries ("host:port" or
    "region=host:port") into CanaryTargets. Returns (targets, malformed)
    so call sites can warn about skipped entries without this module
    taking a logging dependency."""
    out: list[CanaryTarget] = []
    bad: list[str] = []
    for entry in entries or []:
        region, _, hp = str(entry).rpartition("=")
        h, _, p = hp.rpartition(":")
        if not h or not p.isdigit():
            bad.append(str(entry))
            continue
        out.append(CanaryTarget(h, int(p), region=region))
    return out, bad


@dataclass
class ProbeCheck:
    """One probe's verified outcome: `correct` means the decrypted answer
    matched the known plaintext expectation; `status` is the HTTP status
    of the (last) request; `detail` carries expected/observed on mismatch
    for the ledger's failure report."""

    correct: bool
    status: int
    detail: dict = field(default_factory=dict)


class CanaryClient:
    """Golden-transaction executor for one prober (see module docstring).

    `ssl_context` mirrors the real client's TLS posture; `timeout` is the
    per-request socket budget (the prober's per-probe deadline also wraps
    the whole coroutine)."""

    def __init__(self, provider: HomoProvider, population: int = 4,
                 ssl_context=None, timeout: float = 2.0):
        self.provider = provider
        self.schema = list(DEFAULT_SCHEMA)
        if not aes_available():
            # AES-less environments (no `cryptography` package): the
            # canary domain is private and its plaintexts synthetic, so
            # the AES-backed string columns (CHE deterministic, "None"
            # randomized) degrade to the "Plain" null cipher rather than
            # killing the prober at first encrypt. Every probe kind
            # keeps working — SearchEq only compares stored bytes for
            # equality, and determinism is all that route needs.
            self.schema = ["Plain" if s in ("CHE", "None") else s
                           for s in self.schema]
        self.population = max(2, int(population))
        self.ssl_context = ssl_context
        self.timeout = float(timeout)
        # content-addressing salt: two probers (or two runs) must never
        # collide on the same canary keys even with identical plaintexts
        self.salt = secrets.token_hex(8)
        self.rows: list[list] = [self._row(i) for i in range(self.population)]
        # server-assigned SHA-512 keys, filled by populate(); index-aligned
        # with self.rows
        self.keys: list[str] = []
        # the population's ciphertexts, frozen at populate(): PSSE/MSE/
        # RND encryption is randomized, so re-encrypting the same
        # plaintext would content-address to a DIFFERENT key — probes
        # re-put these exact bytes to make the write idempotent
        self.enc_rows: list[list] = []
        self.expected_sum = sum(r[_PSSE_POS] for r in self.rows)
        self.expected_product = 1
        for r in self.rows:
            self.expected_product *= r[_MSE_POS]

    # ------------------------------------------------------------ plaintext

    def _row(self, i: int) -> list:
        """Known plaintext row i. Values are small and distinct so sum /
        product / per-column mismatches are attributable; the salted blob
        column keeps the content-addressed keys unique per prober."""
        return [
            100 + i,                     # OPE
            f"canary-{i}",               # CHE (deterministic: SearchEq target)
            10 + i,                      # PSSE (SumAll ground truth)
            2 + (i % 2),                 # MSE (MultAll ground truth)
            "probe", "of", "light",      # CHE x3
            f"beam-{i}-{self.salt}",     # None (salt -> unique content key)
        ]

    # ----------------------------------------------------------------- wire

    async def _request(self, target: CanaryTarget, method: str, route: str,
                       payload: dict | None = None,
                       trace_id: str | None = None) -> tuple[int, bytes]:
        """One canary HTTP request: the real wire path, tagged with the
        canary tenant and an explicit trace id so every probe's span tree
        is findable from its ledger exemplar."""
        headers = {"x-dds-tenant": CANARY_TENANT}
        if trace_id is not None:
            headers["x-dds-trace"] = trace_id
        body = json.dumps(payload).encode() if payload is not None else None
        return await http_request(
            target.host, target.port, method, route, body,
            ssl_context=self.ssl_context, timeout=self.timeout,
            headers=headers,
        )

    @staticmethod
    def mint_trace() -> str:
        """A fresh trace id for one probe (the exemplar the ledger keeps)."""
        return obs_context.new_id()

    # --------------------------------------------------------------- probes

    async def populate(self, target: CanaryTarget,
                       trace_id: str | None = None) -> None:
        """Store the full known population and freeze its ciphertexts
        (idempotent thereafter: content addressing maps identical
        ciphertexts to identical keys, and the canary tenant owns them).
        Fills `self.keys` / `self.enc_rows`."""
        enc_rows = [
            self.provider.encrypt_row(row, _FIXED_COLUMNS, self.schema)
            for row in self.rows
        ]
        keys = []
        for enc in enc_rows:
            status, body = await self._request(
                target, "POST", "/PutSet", {"contents": enc}, trace_id
            )
            if status != 200:
                raise RuntimeError(f"canary populate PutSet -> {status}")
            keys.append(body.decode())
        self.keys = keys
        self.enc_rows = enc_rows

    async def probe_putget(self, target: CanaryTarget, trace_id: str,
                           cycle: int = 0) -> ProbeCheck:
        """PutSet -> quorum write -> GetSet read-your-write -> decrypt and
        compare. Rotates through the population so every canary key takes
        a fresh quorum write + verified read over `population` cycles."""
        i = cycle % self.population
        row = self.rows[i]
        enc = (self.enc_rows[i] if self.enc_rows
               else self.provider.encrypt_row(row, _FIXED_COLUMNS,
                                              self.schema))
        status, body = await self._request(
            target, "POST", "/PutSet", {"contents": enc}, trace_id
        )
        if status != 200:
            return ProbeCheck(False, status, {"phase": "put"})
        key = body.decode()
        if self.keys and key != self.keys[i]:
            return ProbeCheck(
                False, status,
                {"phase": "put", "expected": self.keys[i], "observed": key},
            )
        status, body = await self._request(
            target, "GET", f"/GetSet/{key}", None, trace_id
        )
        if status != 200:
            return ProbeCheck(False, status, {"phase": "get", "key": key})
        contents = json.loads(body.decode()).get("contents")
        try:
            plain = self.provider.decrypt_row(
                contents, _FIXED_COLUMNS, self.schema
            )
        except Exception as e:
            return ProbeCheck(
                False, status, {"phase": "decrypt", "key": key, "error": str(e)}
            )
        if plain != row:
            return ProbeCheck(
                False, status,
                {"phase": "verify", "key": key,
                 "expected": row, "observed": plain},
            )
        return ProbeCheck(True, status, {"key": key})

    async def probe_sum(self, target: CanaryTarget,
                        trace_id: str) -> ProbeCheck:
        """SumAll over the PSSE column, decrypted and compared against the
        population's known sum — the decrypt-and-verify check that catches
        a wrong-but-well-MAC'd ciphertext."""
        nsqr = self.provider.keys.psse.public.nsquare
        status, body = await self._request(
            target, "GET", f"/SumAll?position={_PSSE_POS}&nsqr={nsqr}",
            None, trace_id,
        )
        if status != 200:
            return ProbeCheck(False, status, {})
        cipher = json.loads(body.decode()).get("result")
        try:
            observed = self.provider.decrypt(cipher, "PSSE")
        except Exception as e:
            return ProbeCheck(False, status, {"phase": "decrypt",
                                              "error": str(e)})
        if observed != self.expected_sum:
            return ProbeCheck(
                False, status,
                {"expected": self.expected_sum, "observed": observed},
            )
        return ProbeCheck(True, status, {})

    async def probe_mult(self, target: CanaryTarget,
                         trace_id: str) -> ProbeCheck:
        """MultAll over the MSE column vs the known product."""
        n = self.provider.keys.mse.n
        status, body = await self._request(
            target, "GET", f"/MultAll?position={_MSE_POS}&pubkey={n}",
            None, trace_id,
        )
        if status != 200:
            return ProbeCheck(False, status, {})
        cipher = json.loads(body.decode()).get("result")
        try:
            observed = self.provider.decrypt(cipher, "MSE")
        except Exception as e:
            return ProbeCheck(False, status, {"phase": "decrypt",
                                              "error": str(e)})
        if observed != self.expected_product:
            return ProbeCheck(
                False, status,
                {"expected": self.expected_product, "observed": observed},
            )
        return ProbeCheck(True, status, {})

    async def probe_search(self, target: CanaryTarget, trace_id: str,
                           cycle: int = 0) -> ProbeCheck:
        """One Spyglass SearchEq on the deterministic CHE column: exactly
        the matching canary key must come back (canary-scoped universe)."""
        i = cycle % self.population
        enc = self.provider.encrypt(self.rows[i][_CHE_POS],
                                    self.schema[_CHE_POS])
        status, body = await self._request(
            target, "POST", f"/SearchEq?position={_CHE_POS}", {"value": enc},
            trace_id,
        )
        if status != 200:
            return ProbeCheck(False, status, {})
        keyset = json.loads(body.decode()).get("keyset", [])
        expected = [self.keys[i]] if self.keys else None
        if expected is not None and sorted(keyset) != sorted(expected):
            return ProbeCheck(
                False, status, {"expected": expected, "observed": keyset},
            )
        return ProbeCheck(True, status, {"matches": len(keyset)})

    async def probe_matvec(self, target: CanaryTarget,
                           trace_id: str) -> ProbeCheck:
        """One Prism MatVec over the PSSE column: a known 2-row weight
        matrix, each output decrypted and compared against the dot product
        recomputed over the RESPONSE's key order (the server sorts keys;
        the prober doesn't assume which order)."""
        p = len(self.rows)
        weights = [[1] * p, [(j % 3) + 1 for j in range(p)]]
        nsqr = self.provider.keys.psse.public.nsquare
        status, body = await self._request(
            target, "POST", f"/MatVec?position={_PSSE_POS}&nsqr={nsqr}",
            {"weights": weights}, trace_id,
        )
        if status != 200:
            return ProbeCheck(False, status, {})
        obj = json.loads(body.decode())
        keys = obj.get("keys", [])
        by_key = dict(zip(self.keys, (r[_PSSE_POS] for r in self.rows)))
        if sorted(keys) != sorted(self.keys):
            return ProbeCheck(
                False, status,
                {"phase": "universe", "expected": sorted(self.keys),
                 "observed": sorted(keys)},
            )
        values = [by_key[k] for k in keys]
        for j, cipher in enumerate(obj.get("result", [])):
            try:
                observed = self.provider.decrypt(cipher, "PSSE")
            except Exception as e:
                return ProbeCheck(False, status, {"phase": "decrypt",
                                                  "row": j, "error": str(e)})
            expected = sum(w * v for w, v in zip(weights[j], values))
            if observed != expected:
                return ProbeCheck(
                    False, status,
                    {"row": j, "expected": expected, "observed": observed},
                )
        return ProbeCheck(True, status, {})

    async def probe(self, kind: str, target: CanaryTarget, trace_id: str,
                    cycle: int = 0) -> ProbeCheck:
        """Dispatch one probe kind (PROBE_KINDS member)."""
        match kind:
            case "putget":
                return await self.probe_putget(target, trace_id, cycle)
            case "sum":
                return await self.probe_sum(target, trace_id)
            case "mult":
                return await self.probe_mult(target, trace_id)
            case "search":
                return await self.probe_search(target, trace_id, cycle)
            case "matvec":
                return await self.probe_matvec(target, trace_id)
        raise ValueError(f"unknown probe kind {kind!r}")


async def build_provider(paillier_bits: int = 512,
                         rsa_bits: int = 512) -> HomoProvider:
    """Generate the canary's private crypto domain off-loop: keygen is
    hundreds of ms of host bignum work, and the prober starts inside the
    proxy's event loop."""
    return await asyncio.to_thread(
        HomoProvider.generate, paillier_bits, rsa_bits
    )
