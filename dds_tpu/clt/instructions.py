"""Client instruction set: the 7 basic + 15 extended operations.

Counterpart of `clt/Instructions.scala` — one dataclass per operation the
workload generator can enqueue, batched in a `Digest`. Values are
*plaintext*; the client encrypts them per-column when building the HTTP
request (the reference does the same, `clt/DDSHttpClient.scala:158-352`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional


@dataclass(frozen=True)
class Digest:
    payload: list  # queue of instructions


# basic API -----------------------------------------------------------------

@dataclass(frozen=True)
class PutSet:
    set: Optional[list]  # None -> empty PutSet (random key)


@dataclass(frozen=True)
class GetSet:
    pass


@dataclass(frozen=True)
class AddElement:
    elem: Any


@dataclass(frozen=True)
class RemoveSet:
    pass


@dataclass(frozen=True)
class WriteElem:
    elem: Any
    pos: int


@dataclass(frozen=True)
class ReadElem:
    pos: int


@dataclass(frozen=True)
class IsElement:
    elem: Any


# extended API --------------------------------------------------------------

@dataclass(frozen=True)
class Sum:
    pos: int


@dataclass(frozen=True)
class SumAll:
    pos: int


@dataclass(frozen=True)
class Mult:
    pos: int


@dataclass(frozen=True)
class MultAll:
    pos: int


@dataclass(frozen=True)
class SearchEq:
    pos: int
    elem: Any


@dataclass(frozen=True)
class SearchNEq:
    pos: int
    elem: Any


@dataclass(frozen=True)
class SearchGt:
    pos: int
    elem: Any


@dataclass(frozen=True)
class SearchGtEq:
    pos: int
    elem: Any


@dataclass(frozen=True)
class SearchLt:
    pos: int
    elem: Any


@dataclass(frozen=True)
class SearchLtEq:
    pos: int
    elem: Any


@dataclass(frozen=True)
class SearchEntry:
    elem: Any


@dataclass(frozen=True)
class SearchEntryOR:
    elem1: Any
    elem2: Any
    elem3: Any


@dataclass(frozen=True)
class SearchEntryAND:
    elem1: Any
    elem2: Any
    elem3: Any


@dataclass(frozen=True)
class OrderLS:
    pos: int


@dataclass(frozen=True)
class OrderSL:
    pos: int
