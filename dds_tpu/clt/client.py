"""Benchmark HTTP client: executes instruction digests with client-side HE.

Counterpart of `clt/DDSHttpClient.scala`: one client holds the HE keys
(`HomoProvider`), load-balances over proxies at random with 3-strike
blacklisting (`:354-406`), encrypts every value before it leaves the
process (`:158-352`), remembers the SHA-512 record keys the proxies return
(`:103-115`), accepts 404s for randomly-targeted keys (`:108`), and reports
wall time + ops/s at the end (`:410-415`).
"""

from __future__ import annotations

import asyncio
import json
import logging
import random
import time
from dataclasses import dataclass, field

from dds_tpu.clt import instructions as I
from dds_tpu.http.miniserver import http_request
from dds_tpu.models.facade import HomoProvider
from dds_tpu.utils.trust import TrustedNodesList

log = logging.getLogger("dds.client")


@dataclass
class ClientConfig:
    proxies: list[str] = field(default_factory=lambda: ["127.0.0.1:8443"])
    request_timeout: float = 10.0
    fixed_columns: int = 8
    schema: list[str] = field(
        default_factory=lambda: ["OPE", "CHE", "PSSE", "MSE", "CHE", "CHE", "CHE", "None"]
    )
    ssl_context: object = None


@dataclass
class RunReport:
    operations: int = 0
    succeeded: int = 0
    not_found: int = 0
    failed: int = 0
    wall_seconds: float = 0.0

    @property
    def ops_per_second(self) -> float:
        return self.operations / self.wall_seconds if self.wall_seconds else 0.0


class DDSHttpClient:
    def __init__(self, provider: HomoProvider, config: ClientConfig | None = None,
                 rng: random.Random | None = None):
        self.provider = provider
        self.cfg = config or ClientConfig()
        self.proxies = TrustedNodesList(self.cfg.proxies, rng)
        self.stored_keys: list[str] = []
        self._rng = rng or random.Random()

    # ------------------------------------------------------------ transport

    async def _request(self, method: str, target: str, obj=None) -> tuple[int, bytes]:
        body = json.dumps(obj).encode() if obj is not None else None
        last_exc: Exception | None = None
        for _ in range(max(1, len(self.proxies.get_trusted()))):
            proxy = self.proxies.defer_to()
            host, _, port = proxy.partition(":")
            try:
                return await http_request(
                    host, int(port), method, target, body,
                    ssl_context=self.cfg.ssl_context,
                    timeout=self.cfg.request_timeout,
                )
            except (OSError, asyncio.TimeoutError) as e:
                # 3 strikes blacklists the proxy (DDSHttpClient.scala:377-398)
                self.proxies.increment_suspicion(proxy)
                last_exc = e
        raise last_exc if last_exc else RuntimeError("no proxies")

    def _random_key(self) -> str | None:
        return self._rng.choice(self.stored_keys) if self.stored_keys else None

    # ------------------------------------------------------------ execution

    def _psse_encrypts_in(self, digest: I.Digest) -> int:
        """How many PSSE encryptions executing `digest` will perform: one
        per PutSet row column whose schema slot is PSSE (the bulk of
        client-side HE cost; reference hot loop SJHomoLibProvider.scala:
        74-86)."""
        psse_cols = [
            i for i, s in enumerate(self.cfg.schema[: self.cfg.fixed_columns])
            if s == "PSSE"
        ]
        count = 0
        for instr in digest.payload:
            if isinstance(instr, I.PutSet) and instr.set is not None:
                count += sum(1 for i in psse_cols if i < len(instr.set))
        return count

    async def execute(self, digest: I.Digest) -> RunReport:
        # bulk-encryption pre-pass: with a provider bulk backend configured,
        # batched device modexps precompute every full-width obfuscator this
        # digest needs, instead of one host modexp per ciphertext. On a
        # worker thread: in single-process deployments this event loop also
        # serves the proxy and replicas, and a large digest's dispatch must
        # not stall them (the proxy's folds make the same to_thread hop).
        if self.provider.bulk_backend is not None:
            count = self._psse_encrypts_in(digest)
            if count:
                await asyncio.to_thread(
                    self.provider.precompute_psse_blinds, count
                )
        report = RunReport()
        t0 = time.perf_counter()
        for instr in digest.payload:
            report.operations += 1
            try:
                status = await self._one(instr)
                if status in (200, 204):
                    report.succeeded += 1
                elif status == 404:
                    report.not_found += 1  # accepted outcome for random keys
                else:
                    report.failed += 1
            except Exception:
                log.exception("instruction failed: %r", instr)
                report.failed += 1
        report.wall_seconds = time.perf_counter() - t0
        log.info(
            "executed %d ops in %.2fs -> %.1f ops/s (%d ok, %d miss, %d failed)",
            report.operations, report.wall_seconds, report.ops_per_second,
            report.succeeded, report.not_found, report.failed,
        )
        return report

    async def _one(self, instr) -> int:
        p, cfg = self.provider, self.cfg
        enc_pos = lambda v, pos: p.encrypt(
            v, cfg.schema[pos] if pos < cfg.fixed_columns else "None"
        )
        psse_nsqr = p.keys.psse.public.nsquare
        mse_n = p.keys.mse.n
        key = self._random_key()

        match instr:
            case I.PutSet(None):
                status, body = await self._request("POST", "/PutSet")
                if status == 200:
                    self.stored_keys.append(body.decode())
                return status
            case I.PutSet(row):
                enc = p.encrypt_row(row, cfg.fixed_columns, cfg.schema)
                status, body = await self._request("POST", "/PutSet", {"contents": enc})
                if status == 200:
                    self.stored_keys.append(body.decode())
                return status
            case I.GetSet():
                if key is None:
                    return 404
                status, _ = await self._request("GET", f"/GetSet/{key}")
                return status
            case I.RemoveSet():
                if key is None:
                    return 404
                status, _ = await self._request("DELETE", f"/RemoveSet/{key}")
                if status == 200 and key in self.stored_keys:
                    self.stored_keys.remove(key)
                return status
            case I.AddElement(elem):
                if key is None:
                    return 404
                status, _ = await self._request(
                    "PUT", f"/AddElement/{key}", {"value": p.encrypt(elem, "None")}
                )
                return status
            case I.WriteElem(elem, pos):
                if key is None:
                    return 404
                status, _ = await self._request(
                    "PUT", f"/WriteElement/{key}?position={pos}",
                    {"value": enc_pos(elem, pos)},
                )
                return status
            case I.ReadElem(pos):
                if key is None:
                    return 404
                status, _ = await self._request("GET", f"/ReadElement/{key}?position={pos}")
                return status
            case I.IsElement(elem):
                if key is None:
                    return 404
                status, _ = await self._request(
                    "POST", f"/IsElement/{key}", {"value": p.encrypt(elem, "CHE")}
                )
                return status
            case I.Sum(pos):
                k1, k2 = self._random_key(), self._random_key()
                if k1 is None or k2 is None:
                    return 404
                status, _ = await self._request(
                    "GET", f"/Sum?key1={k1}&key2={k2}&position={pos}&nsqr={psse_nsqr}"
                )
                return status
            case I.SumAll(pos):
                status, _ = await self._request(
                    "GET", f"/SumAll?position={pos}&nsqr={psse_nsqr}"
                )
                return status
            case I.Mult(pos):
                k1, k2 = self._random_key(), self._random_key()
                if k1 is None or k2 is None:
                    return 404
                status, _ = await self._request(
                    "GET", f"/Mult?key1={k1}&key2={k2}&position={pos}&pubkey={mse_n}"
                )
                return status
            case I.MultAll(pos):
                status, _ = await self._request(
                    "GET", f"/MultAll?position={pos}&pubkey={mse_n}"
                )
                return status
            case I.SearchEq(pos, elem) | I.SearchNEq(pos, elem):
                route = "SearchEq" if isinstance(instr, I.SearchEq) else "SearchNEq"
                status, _ = await self._request(
                    "POST", f"/{route}?position={pos}", {"value": enc_pos(elem, pos)}
                )
                return status
            case (
                I.SearchGt(pos, elem)
                | I.SearchGtEq(pos, elem)
                | I.SearchLt(pos, elem)
                | I.SearchLtEq(pos, elem)
            ):
                route = type(instr).__name__
                status, _ = await self._request(
                    "POST",
                    f"/{route}?position={pos}",
                    {"value": p.encrypt(int(elem), "OPE")},
                )
                return status
            case I.SearchEntry(elem):
                status, _ = await self._request(
                    "POST", "/SearchEntry", {"value": p.encrypt(elem, "LSE")}
                )
                return status
            case I.SearchEntryOR(e1, e2, e3) | I.SearchEntryAND(e1, e2, e3):
                route = (
                    "SearchEntryOR" if isinstance(instr, I.SearchEntryOR) else "SearchEntryAND"
                )
                status, _ = await self._request(
                    "POST",
                    f"/{route}",
                    {
                        "value1": p.encrypt(e1, "LSE"),
                        "value2": p.encrypt(e2, "LSE"),
                        "value3": p.encrypt(e3, "LSE"),
                    },
                )
                return status
            case I.OrderLS(pos) | I.OrderSL(pos):
                route = "OrderLS" if isinstance(instr, I.OrderLS) else "OrderSL"
                status, _ = await self._request("GET", f"/{route}?position={pos}")
                return status
        raise ValueError(f"unknown instruction {instr!r}")
