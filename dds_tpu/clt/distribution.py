"""Workload distributions shared by every traffic source.

The closed-loop client generator (`clt/generator.py`, the reference's
`DDSDataGenerator` counterpart) and the Meridian open-loop load plane
(`fabric/loadgen.py`) must draw rows and values from ONE distribution
module, not forked copies — a benchmark that loads the store with
different data than the correctness workload would measure a different
system. This module owns:

- the typed column-value generators (`generate_column_data`, the
  canonical table at `DDSDataGenerator.scala:271-282`);
- whole-row synthesis (`random_row`: fixed typed prefix + random-length
  plaintext tail, `DDSDataGenerator.scala`'s row shape);
- `ZipfKeys`, the skewed key-popularity distribution every serious load
  generator needs (a handful of hot keys take most of the traffic, the
  long tail keeps the cache honest).
"""

from __future__ import annotations

import bisect
import random
import string

# column type vocabulary, as in DDSDataGenerator.ALLOWED_DATA_TYPES
ALLOWED_DATA_TYPES = (
    "String", "Char", "Int", "Long", "Float", "Double", "Boolean", "Blob"
)


def generate_column_data(ctype: str, rng: random.Random):
    """Random typed value for one column (`DDSDataGenerator.scala:271-282`)."""
    match ctype:
        case "Int":
            return rng.randrange(0, 1 << 16)
        case "Long":
            return rng.randrange(0, 1 << 31)
        case "Float" | "Double":
            # encrypted columns carry ints; floats only appear in the tail
            return round(rng.uniform(0, 1e6), 3)
        case "Char":
            return rng.choice(string.ascii_letters)
        case "Boolean":
            return rng.choice([True, False])
        case "Blob":
            return "".join(rng.choices(string.ascii_letters + string.digits, k=32))
        case _:
            return " ".join(
                "".join(rng.choices(string.ascii_lowercase, k=rng.randrange(3, 9)))
                for _ in range(rng.randrange(1, 4))
            )


def random_row(mappings: list[str], max_nr_of_columns: int,
               rng: random.Random) -> list:
    """One record: every fixed column typed per `mappings`, then a
    random-length tail of randomly-typed values up to
    `max_nr_of_columns` total — the generator's row shape, reused
    verbatim by the load plane's seed phase."""
    fixed = len(mappings)
    row = [generate_column_data(mappings[i], rng) for i in range(fixed)]
    for _ in range(rng.randrange(0, max(1, max_nr_of_columns - fixed + 1))):
        row.append(generate_column_data(rng.choice(ALLOWED_DATA_TYPES), rng))
    return row


class ZipfKeys:
    """Zipf(s) popularity over a fixed key list: P(rank r) ∝ 1/r^s.
    Rank-1 is the hottest key; s=0 degenerates to uniform. Sampling is
    O(log K) via an inverse-CDF bisect over the precomputed harmonic
    prefix sums, so a million-arrival sweep spends its time on I/O, not
    on the distribution."""

    def __init__(self, keys: list[str], s: float = 1.1,
                 rng: random.Random | None = None):
        if not keys:
            raise ValueError("ZipfKeys needs at least one key")
        self.keys = list(keys)
        self.s = float(s)
        self.rng = rng or random.Random()
        acc, cdf = 0.0, []
        for r in range(1, len(self.keys) + 1):
            acc += 1.0 / (r ** self.s)
            cdf.append(acc)
        self._cdf = [c / acc for c in cdf]

    def pick(self) -> str:
        u = self.rng.random()
        return self.keys[bisect.bisect_left(self._cdf, u)]

    def weight(self, rank: int) -> float:
        """P(rank) for tests/reporting (1-indexed)."""
        lo = self._cdf[rank - 2] if rank >= 2 else 0.0
        return self._cdf[rank - 1] - lo
