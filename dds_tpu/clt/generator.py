"""Workload generator: proportions -> shuffled, schema-aware instruction queue.

Counterpart of `clt/DDSDataGenerator.scala:31-269`: each operation count is
round(n * proportion); operations only target columns whose encryption
scheme supports them (Sum needs a PSSE column, range search an OPE column,
entry search an LSE column, ... — the canonical table at
`DDSDataGenerator.scala:11-23`); rows have a fixed encrypted prefix plus a
random-length plaintext-typed tail.

Fixed vs reference (SURVEY.md §7): Mult/MultAll counts use the mult
proportions (the reference reuses the sum-all count, `:159-171`), and
SearchEntryOR uses its own count (reference reuses search-entry's, `:253`).
"""

from __future__ import annotations

import random
from typing import Iterable

from dds_tpu.clt import instructions as I

# value/row distributions live in clt/distribution so the open-loop load
# plane (fabric/loadgen) drives the SAME data shapes this closed-loop
# generator does; re-exported here for compatibility
from dds_tpu.clt.distribution import (  # noqa: F401  (re-exports)
    ALLOWED_DATA_TYPES,
    generate_column_data,
    random_row,
)

DEFAULT_PROPORTIONS = {
    "get-set": 0.0, "put-set": 0.1, "remove-set": 0.0, "add-element": 0.0,
    "read-element": 0.0, "write-element": 0.0, "is-element": 0.0,
    "sum": 0.0, "sum-all": 0.0, "mult": 0.0, "mult-all": 0.0,
    "search-eq": 0.1, "search-neq": 0.1, "search-gt": 0.1, "search-gteq": 0.1,
    "search-lt": 0.1, "search-lteq": 0.1, "order-ls": 0.0, "order-sl": 0.0,
    "search-entry": 0.1, "search-entry-and": 0.1, "search-entry-or": 0.1,
}


def _columns_by_scheme(schema: list[str]) -> dict[str, list[int]]:
    out: dict[str, list[int]] = {s: [] for s in ("OPE", "CHE", "LSE", "PSSE", "MSE", "None")}
    for i, s in enumerate(schema):
        out.setdefault(s, []).append(i)
    return out


def generate(
    nr_of_operations: int,
    proportions: dict[str, float] | None = None,
    max_nr_of_columns: int = 16,
    column_mappings: list[str] | None = None,
    column_encryptions: list[str] | None = None,
    rng: random.Random | None = None,
) -> list:
    """Build the shuffled instruction list for one client run."""
    rng = rng or random.Random()
    if proportions is None:
        props = dict(DEFAULT_PROPORTIONS)
    else:
        unknown = set(proportions) - set(DEFAULT_PROPORTIONS)
        if unknown:
            raise ValueError(f"unknown proportion keys: {sorted(unknown)}")
        # user distribution REPLACES the defaults: unspecified ops are 0,
        # so nr_of_operations matches the requested mix
        props = {k: proportions.get(k, 0.0) for k in DEFAULT_PROPORTIONS}
    mappings = column_mappings or ["Int", "String", "Int", "Int", "String", "String", "String", "Blob"]
    schema = column_encryptions or ["OPE", "CHE", "PSSE", "MSE", "CHE", "CHE", "CHE", "None"]
    cols = _columns_by_scheme(schema)
    fixed = len(schema)

    def count(op: str) -> int:
        return round(nr_of_operations * props.get(op, 0.0))

    def rand_row() -> list:
        return random_row(mappings[:fixed], max_nr_of_columns, rng)

    def pick(scheme_cols: Iterable[str]) -> list[int]:
        merged: list[int] = []
        for s in scheme_cols:
            merged.extend(cols.get(s, []))
        return merged

    out: list = []
    out += [I.PutSet(rand_row()) for _ in range(count("put-set"))]
    out += [I.GetSet() for _ in range(count("get-set"))]
    out += [I.RemoveSet() for _ in range(count("remove-set"))]
    out += [I.AddElement(generate_column_data("String", rng)) for _ in range(count("add-element"))]
    out += [
        I.WriteElem(generate_column_data("String", rng), fixed + rng.randrange(4))
        for _ in range(count("write-element"))
    ]
    out += [I.ReadElem(rng.randrange(fixed)) for _ in range(count("read-element"))]

    che = pick(["CHE"])
    out += [
        I.IsElement(generate_column_data("String", rng))
        for _ in range(count("is-element"))
        if che
    ]

    psse, mse, ope, lse = pick(["PSSE"]), pick(["MSE"]), pick(["OPE"]), pick(["LSE"])
    if psse:
        out += [I.Sum(rng.choice(psse)) for _ in range(count("sum"))]
        out += [I.SumAll(rng.choice(psse)) for _ in range(count("sum-all"))]
    if mse:
        out += [I.Mult(rng.choice(mse)) for _ in range(count("mult"))]
        out += [I.MultAll(rng.choice(mse)) for _ in range(count("mult-all"))]
    eq_cols = ope + che
    if eq_cols:
        for op, n in ((I.SearchEq, count("search-eq")), (I.SearchNEq, count("search-neq"))):
            for _ in range(n):
                pos = rng.choice(eq_cols)
                ctype = mappings[pos] if schema[pos] == "OPE" else "String"
                out.append(op(pos, generate_column_data(ctype, rng)))
    if ope:
        for op, n in (
            (I.SearchGt, count("search-gt")),
            (I.SearchGtEq, count("search-gteq")),
            (I.SearchLt, count("search-lt")),
            (I.SearchLtEq, count("search-lteq")),
        ):
            out += [op(rng.choice(ope), generate_column_data("Int", rng)) for _ in range(n)]
        out += [I.OrderLS(rng.choice(ope)) for _ in range(count("order-ls"))]
        out += [I.OrderSL(rng.choice(ope)) for _ in range(count("order-sl"))]
    if lse:
        word = lambda: generate_column_data("String", rng)
        out += [I.SearchEntry(word()) for _ in range(count("search-entry"))]
        out += [
            I.SearchEntryOR(word(), word(), word()) for _ in range(count("search-entry-or"))
        ]
        out += [
            I.SearchEntryAND(word(), word(), word())
            for _ in range(count("search-entry-and"))
        ]

    rng.shuffle(out)
    return out
