"""Tier-5 client harness: instruction set, workload generator, bench client."""

from dds_tpu.clt.instructions import Digest  # noqa: F401
from dds_tpu.clt.generator import generate  # noqa: F401
from dds_tpu.clt.client import DDSHttpClient, ClientConfig  # noqa: F401
