"""Meridian cross-host reshard plumbing: agents and remote group handles.

`shard/rebalance.Rebalancer` was written against the in-process
`ShardGroup` handle: freeze = a synchronous `state.install`, the seed
export a direct repository read, the post-activation prune a method call.
Across hosts those three become control-plane RPCs; everything ELSE the
rebalancer does (manifest collection, chunk streaming, ack quorums)
already rides plain transport messages and needs no change.

- `MeridianAgent` runs in every group process, registered at
  `<host:port>/<gid>-fabric`. It installs signed maps into the group's
  shared fencing state (freeze / rollback), adopts activations into the
  process's serving view (waking `/shards` long-polls), exports a
  replica's repository as migration seed data, and prunes after cut-over.
- `AgentClient` + `RemoteShardGroup` live in the controller (proxy)
  process and present the exact `ShardGroup` surface the Rebalancer
  expects — `state.install` / `export_from` / `prune_unowned` return
  awaitables, which the rebalancer now awaits when it gets one.

Trust: the map is HMAC-signed and re-verified at the agent, so install/
activate frames only need delivery. Export returns DATA (every receiving
replica re-verifies entries against the attested manifest quorum), and
prune only drops keys the group's OWN fencing map disowns. The frames
ride the authenticated transport (frame MAC / nodeauth / intranet TLS),
the same trust the Kill/Redeploy control messages already ride.
"""

from __future__ import annotations

import asyncio
import logging

from dds_tpu.core import messages as M
from dds_tpu.shard.shardmap import ShardMap
from dds_tpu.utils import sigs
from dds_tpu.utils.retry import Deadline, RetryPolicy, retry_deadline

log = logging.getLogger("dds.fabric.remote")


class MeridianAgent:
    """Per-group-process control endpoint for the fabric RPCs."""

    def __init__(self, net, addr: str, group, view, secret: bytes,
                 hub=None):
        self.net = net
        self.addr = addr
        self.group = group          # shard.fabric.ShardGroup (local)
        self.view = view            # RemoteShardManager serving-view mirror
        self.secret = secret
        self.hub = hub
        net.register(addr, self.handle)

    def stop(self) -> None:
        self.net.unregister(self.addr)

    def _ack(self, dest: str, nonce: int, ok: bool, error: str = "") -> None:
        self.net.send(self.addr, dest,
                      M.ShardMapAck(nonce, self.group.state.epoch, ok, error))

    async def handle(self, sender: str, msg) -> None:
        if isinstance(msg, M.ShardMapInstall):
            try:
                smap = ShardMap.from_wire(msg.map)
                self.group.state.install(smap, force=msg.force,
                                         lease=getattr(msg, "lease", 0.0))
            except (ValueError, KeyError, TypeError) as e:
                log.warning("refused shard-map install from %s: %s",
                            sender, e)
                self._ack(sender, msg.nonce, False, str(e))
                return
            self._ack(sender, msg.nonce, True)
        elif isinstance(msg, M.ShardMapActivate):
            try:
                smap = ShardMap.from_wire(msg.map)
                self.view.install(smap)          # verifies + notifies hub
                # fencing follows the active map epoch-forward; >= so an
                # activation also COMMITS the equal-epoch map the freeze
                # installed under a fence lease
                if smap.epoch >= self.group.state.epoch:
                    self.group.state.install(smap)
            except (ValueError, KeyError, TypeError) as e:
                log.warning("refused shard-map activate from %s: %s",
                            sender, e)
                self._ack(sender, msg.nonce, False, str(e))
                return
            self._ack(sender, msg.nonce, True)
        elif isinstance(msg, M.ShardExportRequest):
            entries = self.group.export_from(msg.endpoint)
            self.net.send(self.addr, sender, M.ShardExport(msg.nonce, entries))
        elif isinstance(msg, M.ShardPruneRequest):
            dropped = self.group.prune_unowned()
            self.net.send(self.addr, sender, M.ShardPruned(msg.nonce, dropped))


class AgentError(RuntimeError):
    """An agent refused an RPC (bad map, backwards epoch) — definitive,
    never retried. The rebalancer's generic failure path aborts the plan
    safely."""


class AgentTimeout(AgentError):
    """An agent did not answer within one attempt's timeout — the only
    retryable agent failure. `AgentClient._call` retries these under the
    call's `Deadline` budget; when the budget runs out the typed
    `DeadlineExceededError` propagates and the rebalancer maps it to a
    plan ABORT instead of hanging mid-reshard."""


class AgentClient:
    """Controller-side RPC endpoint: correlates nonced requests to agent
    replies. One instance serves every remote group.

    Every control RPC runs under a `utils/retry.Deadline`: `timeout` is
    the per-ATTEMPT wait, `budget` the total time a call may spend across
    attempts (jittered exponential backoff between them). Lost frames and
    a briefly-restarting agent are retried away; a refusal (signed-map
    verification, backwards epoch) is definitive and never retried."""

    def __init__(self, net, addr: str, timeout: float = 5.0,
                 budget: float | None = None):
        self.net = net
        self.addr = addr
        self.timeout = timeout
        # default: room for ~3 full attempts plus backoff
        self.budget = budget if budget is not None else 3.5 * timeout
        self.policy = RetryPolicy(base=0.05, multiplier=2.0, max_delay=1.0)
        self._pending: dict[int, asyncio.Future] = {}
        net.register(addr, self.handle)

    def stop(self) -> None:
        self.net.unregister(self.addr)

    async def handle(self, sender: str, msg) -> None:
        nonce = getattr(msg, "nonce", None)
        fut = self._pending.get(nonce)
        if fut is not None and not fut.done():
            fut.set_result(msg)

    async def _call_once(self, agent: str, make_msg, timeout: float):
        nonce = sigs.generate_nonce()
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[nonce] = fut
        try:
            self.net.send(self.addr, agent, make_msg(nonce))
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            raise AgentTimeout(f"agent {agent} did not answer")
        finally:
            self._pending.pop(nonce, None)

    async def _call(self, agent: str, make_msg, *,
                    timeout: float | None = None,
                    deadline: Deadline | None = None):
        per_attempt = timeout or self.timeout
        deadline = deadline or Deadline(max(self.budget, per_attempt))

        async def attempt():
            t = deadline.timeout(per_attempt)
            if t <= 0:
                raise AgentTimeout(f"agent {agent}: no budget left")
            return await self._call_once(agent, make_msg, t)

        # only AgentTimeout retries; refusals propagate immediately. A
        # spent deadline surfaces as DeadlineExceededError -> plan abort.
        return await retry_deadline(attempt, deadline, self.policy,
                                    retry_on=(AgentTimeout,))

    async def install(self, agent: str, smap: ShardMap,
                      force: bool = False, lease: float = 0.0,
                      deadline: Deadline | None = None) -> None:
        wire = smap.to_wire()
        reply = await self._call(
            agent, lambda n: M.ShardMapInstall(wire, force, n, lease),
            deadline=deadline,
        )
        if not isinstance(reply, M.ShardMapAck) or not reply.ok:
            raise AgentError(
                f"agent {agent} refused map install: "
                f"{getattr(reply, 'error', 'bad reply')!r}"
            )

    async def activate(self, agent: str, smap: ShardMap,
                       deadline: Deadline | None = None) -> None:
        wire = smap.to_wire()
        reply = await self._call(agent, lambda n: M.ShardMapActivate(wire, n),
                                 deadline=deadline)
        if not isinstance(reply, M.ShardMapAck) or not reply.ok:
            raise AgentError(
                f"agent {agent} refused map activate: "
                f"{getattr(reply, 'error', 'bad reply')!r}"
            )

    async def export(self, agent: str, endpoint: str,
                     timeout: float | None = None,
                     deadline: Deadline | None = None) -> dict:
        reply = await self._call(
            agent, lambda n: M.ShardExportRequest(endpoint, n),
            timeout=timeout,
            deadline=deadline or Deadline(
                max(self.budget, timeout or self.timeout)
            ),
        )
        if not isinstance(reply, M.ShardExport):
            raise AgentError(f"agent {agent} sent a bad export reply")
        return dict(reply.entries)

    async def prune(self, agent: str,
                    deadline: Deadline | None = None) -> int:
        reply = await self._call(agent, lambda n: M.ShardPruneRequest(n),
                                 deadline=deadline)
        if not isinstance(reply, M.ShardPruned):
            raise AgentError(f"agent {agent} sent a bad prune reply")
        return int(reply.dropped)


class _RemoteGroupState:
    """`ShardState`-shaped fencing handle whose `install` returns an
    awaitable resolving when the remote agent acked (shard/rebalance
    awaits whatever `install` returns)."""

    def __init__(self, rpc: AgentClient, agent: str):
        self._rpc = rpc
        self._agent = agent

    def install(self, smap: ShardMap, force: bool = False,
                lease: float = 0.0):
        return self._rpc.install(self._agent, smap, force=force, lease=lease)


class RemoteShardGroup:
    """The rebalancer-facing handle for a group hosted in ANOTHER
    process: same attribute surface as `shard.fabric.ShardGroup`, with
    the three state-touching calls returning awaitables over the agent
    RPCs. Replica/supervisor addresses are derived from the fabric
    config's per-group host:port and the homogeneous shard geometry —
    the same derivation every process in the fleet applies."""

    def __init__(self, gid: str, hostport: str, *, n_active: int,
                 n_sentinent: int, quorum: int, rpc: AgentClient,
                 export_timeout: float = 10.0):
        self.gid = gid
        self.hostport = hostport
        self.active = [
            f"{hostport}/{gid}-replica-{i}" for i in range(n_active)
        ]
        self.sentinent = [
            f"{hostport}/{gid}-replica-{i}"
            for i in range(n_active, n_active + n_sentinent)
        ]
        self.quorum_size = quorum
        self.agent = f"{hostport}/{gid}-fabric"
        self.state = _RemoteGroupState(rpc, self.agent)
        self._rpc = rpc
        self._export_timeout = export_timeout

    def all_replicas(self) -> list[str]:
        return self.active + self.sentinent

    def export_from(self, endpoint: str):
        if endpoint is None:
            async def _empty():
                return {}
            return _empty()
        return self._rpc.export(self.agent, endpoint,
                                timeout=self._export_timeout)

    def prune_unowned(self):
        return self._rpc.prune(self.agent)
