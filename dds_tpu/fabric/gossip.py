"""Meridian shard-map distribution: bootstrap + epoch-gossip freshness.

A multi-host constellation has one piece of shared routing state — the
signed, epoch-versioned `ShardMap` — and three kinds of consumers that
must stay fresh without an operator in the loop:

- **remote proxies** bootstrap the map from any peer's signed
  `GET /shards` and then hold a long-poll (`If-None-Match: <epoch>` +
  `?wait=<s>`) that returns 304 when nothing changed and the full signed
  map the moment an epoch bump lands — change notification, not hot
  polling;
- **group processes** mirror the active map the same way so any of them
  can serve `/shards` to a (re)starting proxy;
- **the serving side** parks those long-polls on an `EpochGossipHub` and
  wakes them from the reshard activation path.

Trust never rides the HTTP hop: every installed map re-verifies its HMAC
(intranet secret) and epochs only move forward, so a malicious or stale
peer can stall freshness but never re-home the keyspace
(shard/shardmap.ShardState has the same contract at the fencing layer).
"""

from __future__ import annotations

import asyncio
import json
import logging

from dds_tpu.http.miniserver import http_request_full
from dds_tpu.obs.metrics import metrics
from dds_tpu.utils.tasks import supervised_task
from dds_tpu.shard.shardmap import ShardMap

log = logging.getLogger("dds.fabric.gossip")


class EpochGossipHub:
    """Server-side wakeup fan-out for `/shards` long-polls: waiters grab
    the CURRENT event and sleep on it; `notify()` swaps in a fresh event
    and fires the old one, waking every parked poller exactly once per
    change. Callers re-check the epoch around the wait — the hub carries
    no state of its own, so a notify racing a subscribe degrades to one
    spurious re-check, never a lost wakeup."""

    def __init__(self):
        self._event = asyncio.Event()

    def notify(self) -> None:
        event, self._event = self._event, asyncio.Event()
        event.set()

    async def wait_change(self, timeout: float) -> bool:
        """True when a change fired within `timeout` seconds."""
        event = self._event
        try:
            await asyncio.wait_for(event.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False


class RemoteShardManager:
    """A router-facing mirror of `shard.ShardManager` for processes that
    do NOT own the map (remote proxies, group-process status views).
    Same read surface — `current()` / `epoch` / `state` — plus a verified
    forward-only `install()` fed by bootstrap/gossip, and the
    begin/end/activate hooks the Rebalancer drives when THIS process is
    the one running a split."""

    def __init__(self, smap: ShardMap, secret: bytes, hub=None,
                 on_install=None):
        if not smap.verify(secret):
            raise ValueError("shard map signature invalid")
        self.secret = secret
        self._map = smap
        self.state = "stable"  # stable | resharding
        self.hub = hub
        # on_install(new_map, old_map) fires after every adopted map — the
        # proxy plugs its new-group client factory here
        self.on_install = on_install

    def current(self) -> ShardMap:
        return self._map

    @property
    def epoch(self) -> int:
        return self._map.epoch

    def install(self, smap: ShardMap, state: str | None = None) -> bool:
        """Adopt a newer signed map; returns True when the epoch moved.
        Backwards/same epochs are ignored (gossip redelivery is normal),
        forged signatures raise."""
        if not smap.verify(self.secret):
            raise ValueError("shard map signature invalid")
        if state is not None and state in ("stable", "resharding"):
            self.state = state
        if smap.epoch <= self._map.epoch:
            return False
        old, self._map = self._map, smap
        metrics.set("dds_shard_epoch", smap.epoch,
                    help="active shard-map epoch")
        if self.on_install is not None:
            try:
                self.on_install(smap, old)
            except Exception:
                log.exception("shard-map install hook failed")
        if self.hub is not None:
            self.hub.notify()
        return True

    def install_wire(self, wire: dict, state: str | None = None) -> bool:
        return self.install(ShardMap.from_wire(wire), state=state)

    # Rebalancer-facing surface (when this process drives a split)
    def begin_reshard(self) -> None:
        self.state = "resharding"

    def end_reshard(self) -> None:
        self.state = "stable"

    def activate(self, smap: ShardMap) -> None:
        if smap.epoch <= self._map.epoch:
            raise ValueError(
                f"activation requires a newer epoch "
                f"({smap.epoch} <= {self._map.epoch})"
            )
        self.install(smap)


async def fetch_shards(peer: str, *, etag: int | None = None,
                       wait: float = 0.0, timeout: float = 5.0,
                       ssl_context=None):
    """One `GET /shards` against `peer` ("host:port"). Returns the parsed
    body dict, or None on 304 (fresh). Raises OSError-family on transport
    trouble — callers rotate peers."""
    host, _, port = peer.partition(":")
    target = "/shards"
    headers = {}
    if wait > 0:
        target += f"?wait={wait:g}"
    if etag is not None:
        headers["If-None-Match"] = f'"{etag}"'
    status, _, body = await http_request_full(
        host, int(port), "GET", target, headers=headers,
        ssl_context=ssl_context, timeout=timeout + wait,
    )
    if status == 304:
        return None
    if status != 200:
        raise ConnectionError(f"/shards on {peer} answered {status}")
    return json.loads(body)


async def bootstrap_map(peers: list[str], secret: bytes, *,
                        timeout: float = 3.0, ssl_context=None):
    """First reachable peer's verified signed map. Returns
    (ShardMap, status body) or (None, None) when nobody answered — the
    caller falls back to the deterministic epoch-1 map from config, and
    the follower keeps trying."""
    for peer in peers:
        try:
            body = await fetch_shards(peer, timeout=timeout,
                                      ssl_context=ssl_context)
        except (OSError, ValueError, EOFError, asyncio.TimeoutError,
                ConnectionError) as e:
            log.debug("shard-map bootstrap from %s failed: %s", peer, e)
            continue
        try:
            smap = ShardMap.from_wire(body["map"])
        except (KeyError, TypeError, ValueError) as e:
            log.warning("malformed /shards body from %s: %s", peer, e)
            continue
        if not smap.verify(secret):
            log.warning("peer %s served a forged shard map — skipped", peer)
            continue
        log.info("bootstrapped shard map epoch %d from %s", smap.epoch, peer)
        return smap, body
    return None, None


class MapFollower:
    """The remote router's freshness loop: long-poll `/shards` across the
    configured peers with `If-None-Match` so a fresh map costs one header
    exchange (304) per `wait` window and an epoch bump arrives the moment
    the serving side's hub fires. `poke()` (the router's WrongShard
    refresh hook) breaks the current wait and refetches immediately."""

    def __init__(self, manager, peers: list[str], secret: bytes, *,
                 wait: float = 25.0, retry: float = 0.5,
                 ssl_context=None, install_also=()):
        self.manager = manager
        self.peers = list(peers)
        self.secret = secret
        self.wait = wait
        self.retry = retry
        self.ssl_context = ssl_context
        # extra fencing states (shard.ShardState) that adopt every map the
        # follower installs — a group process keeps its replicas' shared
        # fence in lockstep with its serving view
        self.install_also = list(install_also)
        self._task: asyncio.Task | None = None
        self._poke = asyncio.Event()

    def poke(self) -> None:
        self._poke.set()

    def start(self) -> None:
        if self.peers and (self._task is None or self._task.done()):
            self._task = supervised_task(self._loop(),
                                         name="gossip.map_follower")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def _install(self, body: dict) -> None:
        smap = ShardMap.from_wire(body["map"])
        changed = self.manager.install(smap, state=body.get("state"))
        if changed:
            metrics.inc("dds_fabric_gossip_updates_total",
                        help="shard-map epochs adopted via gossip")
        for state in self.install_also:
            try:
                if smap.epoch > state.epoch:
                    state.install(smap)
            except ValueError:
                log.exception("gossiped map refused by fencing state")

    async def sync_once(self) -> bool:
        """One immediate refresh attempt across the peers (no long-poll).
        True when any peer answered (fresh or newer)."""
        for peer in self.peers:
            try:
                body = await fetch_shards(
                    peer, etag=self.manager.epoch, timeout=self.retry + 2.0,
                    ssl_context=self.ssl_context,
                )
            except (OSError, ValueError, EOFError, asyncio.TimeoutError,
                    ConnectionError):
                continue
            if body is not None:
                self._install(body)
            return True
        return False

    async def _loop(self) -> None:
        i = 0
        loop = asyncio.get_running_loop()
        while True:
            peer = self.peers[i % len(self.peers)]
            poked = self._poke.is_set()
            self._poke.clear()
            t0 = loop.time()
            try:
                body = await fetch_shards(
                    peer, etag=self.manager.epoch,
                    # a poke wants the answer NOW, not after a parked poll
                    wait=0.0 if poked else self.wait,
                    timeout=self.retry + 5.0, ssl_context=self.ssl_context,
                )
                if body is not None:
                    self._install(body)
                elif not poked and loop.time() - t0 < min(0.05, self.wait):
                    # a peer that answers 304 without holding the poll
                    # (wait unsupported or zero) must not become a hot
                    # polling loop — pace to the retry interval
                    await asyncio.sleep(self.retry)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.debug("gossip poll of %s failed: %s", peer, e)
                i += 1  # rotate to the next peer
                # back off, but wake instantly on a poke
                try:
                    await asyncio.wait_for(self._poke.wait(), self.retry)
                except asyncio.TimeoutError:
                    pass
