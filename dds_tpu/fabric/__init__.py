"""Meridian: the multi-host shard fabric.

Takes the Constellation sharding plane (dds_tpu/shard) across process
and host boundaries: per-group `TcpNet` deployment driven by a `[fabric]`
config role, signed shard-map distribution via `GET /shards` bootstrap +
epoch-gossip long-polls (304 when fresh, a push the moment an epoch
bumps), cross-host live resharding through per-group control agents, and
an open-loop load plane (`fabric.loadgen`) that drives the fleet like a
million impatient users and reports through the SLO engine. DEPLOY.md
"Multi-host (Meridian)" is the runbook.
"""

from dds_tpu.fabric.deploy import (
    FabricStatusServer,
    MeridianController,
    group_endpoints,
    initial_map,
    launch_meridian,
    parse_role,
)
from dds_tpu.fabric.gossip import (
    EpochGossipHub,
    MapFollower,
    RemoteShardManager,
    bootstrap_map,
    fetch_shards,
)
from dds_tpu.fabric.remote import (
    AgentClient,
    AgentError,
    MeridianAgent,
    RemoteShardGroup,
)

__all__ = [
    "FabricStatusServer", "MeridianController", "group_endpoints",
    "initial_map", "launch_meridian", "parse_role",
    "EpochGossipHub", "MapFollower", "RemoteShardManager",
    "bootstrap_map", "fetch_shards",
    "AgentClient", "AgentError", "MeridianAgent", "RemoteShardGroup",
]
