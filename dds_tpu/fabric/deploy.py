"""Meridian role deployment: one TOML, N OS processes, one constellation.

`run.launch` lands here when `shard.enabled` meets `transport.kind =
"tcp"`. The `[fabric]` section names every group's transport address and
this process's `role`; the three roles compose a fleet:

- **all** — the full constellation (S groups + ShardRouter + REST proxy)
  in one process over real sockets: the single-box production posture
  and the bring-up smoke for the multi-process one.
- **group:N** — quorum group sN only: replicas + spares + supervisor +
  anti-entropy + Trudy over this process's `TcpNet`, a `MeridianAgent`
  control endpoint (`<host:port>/sN-fabric`) for cross-host freezes/
  activations/exports/prunes, and a status listener serving the signed
  map at `GET /shards` (with 304/long-poll gossip) so proxies can
  bootstrap from any surviving group.
- **proxy** — the REST proxy + ShardRouter only: bootstraps the signed
  map from `fabric.bootstrap` peers, keeps it fresh with epoch-gossip
  long-polls, derives every group's replica addresses from the shared
  config, and hosts the `MeridianController` that drives cross-host
  `Rebalancer.split`s (exposed at `POST /_reshard` with `admin-routes`).

Every process derives the SAME epoch-1 map (`ShardMap.build` is
deterministic over the group list) and verifies every later map against
the shared intranet secret, so fleet bring-up has no ordering
constraints: a proxy started before its groups serves 503s until quorums
appear, never wrong answers.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import types

from dds_tpu.fabric.gossip import (
    EpochGossipHub,
    MapFollower,
    RemoteShardManager,
    bootstrap_map,
)
from dds_tpu.fabric.remote import AgentClient, MeridianAgent, RemoteShardGroup
from dds_tpu.http.miniserver import HttpServer, Request, Response
from dds_tpu.http.server import DDSRestServer
from dds_tpu.obs.metrics import metrics
from dds_tpu.obs.slo import SloEngine
from dds_tpu.shard.fabric import build_constellation, build_group
from dds_tpu.shard.rebalance import Rebalancer
from dds_tpu.shard.router import ShardRouter
from dds_tpu.shard.shardmap import ShardManager, ShardMap, ShardState

log = logging.getLogger("dds.fabric")


def parse_role(role: str) -> tuple[str, str | None]:
    """("all"|"proxy"|"group", gid|None) from a [fabric] role string.
    Accepts "group:2" (-> "s2") and "group:s2"."""
    role = (role or "all").strip()
    if role in ("all", "proxy"):
        return role, None
    kind, sep, which = role.partition(":")
    if kind == "group" and sep:
        which = which.strip()
        if which.isdigit():
            return "group", f"s{int(which)}"
        if which:
            return "group", which
    raise ValueError(
        f"unknown fabric role {role!r} (expected 'all', 'proxy', or "
        f"'group:<N>')"
    )


def initial_map(cfg) -> ShardMap:
    """The deterministic epoch-1 map every process derives from [shard]."""
    gids = [f"s{i}" for i in range(cfg.shard.count)]
    return ShardMap.build(gids, cfg.shard.vnodes_per_group).sign(
        cfg.security.abd_mac_secret.encode()
    )


def group_endpoints(cfg, gid: str) -> tuple[list[str], list[str]]:
    """(active, sentinent) full replica addresses for `gid`, derived from
    fabric.groups + the homogeneous [shard] geometry — identical in every
    process of the fleet."""
    hostport = cfg.fabric.groups.get(gid)
    if not hostport:
        raise ValueError(
            f"group {gid!r} has no [fabric.groups] transport address"
        )
    n_act, n_sen = cfg.shard.replicas_per_group, cfg.shard.sentinent_per_group
    active = [f"{hostport}/{gid}-replica-{i}" for i in range(n_act)]
    sentinent = [
        f"{hostport}/{gid}-replica-{i}" for i in range(n_act, n_act + n_sen)
    ]
    return active, sentinent


def _groups_body(cfg, smap: ShardMap) -> dict:
    out = {}
    for gid in smap.groups:
        try:
            active, _ = group_endpoints(cfg, gid)
        except ValueError:
            log.warning("group %s missing from [fabric.groups]", gid)
            continue
        out[gid] = active
    return out


class _Stopper:
    """Adapter: any callable (sync or async) as a Deployment stoppable."""

    def __init__(self, fn):
        self._fn = fn

    async def stop(self):
        res = self._fn()
        if asyncio.iscoroutine(res):
            await res


class FabricStatusServer:
    """The group-role status listener: GET /shards (signed map, ETag/304
    + long-poll gossip), /health, /metrics — enough surface for proxies
    to bootstrap from and operators to watch, without a storage router
    in the process."""

    def __init__(self, host: str, port: int, view, groups_fn, hub,
                 *, group=None, gid: str = "", wait_cap: float = 60.0,
                 ssl_context=None):
        self.view = view
        self.groups_fn = groups_fn
        self.hub = hub
        self.group = group
        self.gid = gid
        self.wait_cap = wait_cap
        self._http = HttpServer(host, port, self.handle, ssl_context)
        self.cfg = types.SimpleNamespace(host=host, port=port)

    async def start(self) -> None:
        await self._http.start()
        self.cfg.port = self._http.port

    async def stop(self) -> None:
        await self._http.stop()

    def status(self) -> dict:
        return {
            "state": self.view.state,
            "map": self.view.current().to_wire(),
            "groups": self.groups_fn(),
        }

    async def handle(self, req: Request) -> Response:
        if req.method != "GET":
            return Response(405)
        route = req.path.strip("/")
        if route == "shards":
            etag = req.headers.get("if-none-match", "").strip().strip('"')
            if etag and etag == str(self.view.epoch):
                try:
                    wait = float(req.query.get("wait", 0) or 0)
                except ValueError:
                    wait = 0.0
                if wait > 0 and self.hub is not None:
                    await self.hub.wait_change(min(wait, self.wait_cap))
                if etag == str(self.view.epoch):
                    return Response(
                        304, headers={"ETag": f'"{self.view.epoch}"'}
                    )
            resp = Response.json(self.status())
            resp.headers["ETag"] = f'"{self.view.epoch}"'
            return resp
        if route == "health":
            body = {
                "status": "ok",
                "role": "group",
                "group": self.gid,
                "shard_epoch": self.view.epoch,
                "reshard_state": self.view.state,
            }
            if self.group is not None:
                body["fence_epoch"] = self.group.state.epoch
                body["replicas"] = {
                    n.name: len(n.repository)
                    for n in self.group.replicas.values()
                }
            return Response.json(body)
        if route == "metrics":
            return Response(
                200, metrics.render().encode(),
                content_type="text/plain; version=0.0.4; charset=utf-8",
            )
        return Response(404)


class MeridianController:
    """Cross-host reshard driver, hosted in a proxy (or all-role)
    process: wraps the Rebalancer with RemoteShardGroup handles derived
    from the fabric config and broadcasts every activation to the fleet's
    group agents so remote /shards views and long-pollers see the epoch
    bump immediately."""

    def __init__(self, cfg, net, namer, manager, rpc: AgentClient):
        self.cfg = cfg
        self.fab = cfg.fabric
        self.sh = cfg.shard
        self.manager = manager
        self.rpc = rpc
        self.rebalancer = Rebalancer(
            manager, net, cfg.security.abd_mac_secret.encode(),
            addr=namer("rebalancer"),
            manifest_timeout=self.sh.manifest_timeout,
            ack_timeout=self.sh.ack_timeout,
            chunk_keys=self.sh.migrate_chunk_keys,
            fence_lease=self.sh.fence_lease,
            journal_dir=self.sh.plan_dir or None,
            on_activate=self.broadcast_activation,
        )

    @property
    def phase(self):
        return self.rebalancer.phase

    def retry_after(self) -> float:
        return self.rebalancer.retry_after()

    async def recover(self) -> str | None:
        """Resolve a plan a crashed controller left in the journal —
        called once at proxy boot, before any new plan can start."""
        return await self.rebalancer.recover(self.handle_for)

    def handle_for(self, gid: str) -> RemoteShardGroup:
        hostport = self.fab.groups.get(gid)
        if not hostport:
            raise ValueError(
                f"group {gid!r} has no [fabric.groups] transport address"
            )
        return RemoteShardGroup(
            gid, hostport,
            n_active=self.sh.replicas_per_group,
            n_sentinent=self.sh.sentinent_per_group,
            quorum=self.sh.quorum_size,
            rpc=self.rpc,
        )

    def pick_target(self, smap: ShardMap) -> str:
        """First configured standby group not yet in the map."""
        for gid in sorted(self.fab.groups):
            if gid not in smap.groups:
                return gid
        raise ValueError(
            "no standby group in [fabric.groups] to split into"
        )

    async def split(self, source: str, target: str | None = None) -> ShardMap:
        smap = self.manager.current()
        if source not in smap.groups:
            raise ValueError(f"unknown source group {source!r}")
        target = target or self.pick_target(smap)
        if target in smap.groups:
            raise ValueError(f"target group {target!r} already in the map")
        return await self.rebalancer.split(
            self.handle_for(source), self.handle_for(target)
        )

    async def merge(self, source: str) -> ShardMap:
        """Fold `source`'s keyspace back into its ring successors and
        retire it to standby (it stays launched and configured, so the
        next split can reuse it)."""
        smap = self.manager.current()
        if source not in smap.groups:
            raise ValueError(f"unknown source group {source!r}")
        if len(smap.groups) < 2:
            raise ValueError("cannot merge the last group away")
        receivers = [self.handle_for(g) for g in smap.absorbers(source)]
        return await self.rebalancer.merge(self.handle_for(source),
                                           receivers)

    async def promote(self, dead: str) -> ShardMap:
        """Disaster takeover for a DEAD group process: relabel its ring
        arcs — same positions, epoch+1 — onto a configured standby whose
        process is alive, freeze-commit the takeover map on the standby,
        activate, and broadcast. Availability over data: whole-group loss
        is beyond the <= f fault model, so the slice restarts empty."""
        from dds_tpu.obs.flight import flight

        smap = self.manager.current()
        if dead not in smap.groups:
            raise ValueError(f"unknown group {dead!r}")
        standby = self.pick_target(smap)
        new_map = smap.relabel(dead, standby).sign(
            self.cfg.security.abd_mac_secret.encode()
        )
        # the standby must hold the takeover map BEFORE routing reaches
        # it (acked install, no lease: this is a commit, not a plan)
        await self.handle_for(standby).state.install(new_map)
        self.manager.activate(new_map)
        await self.broadcast_activation(new_map)
        await flight.record_async("takeover", dead=dead, standby=standby,
                                  epoch=new_map.epoch)
        return new_map

    async def broadcast_activation(self, smap: ShardMap) -> None:
        """Push the activated map to every configured group agent (the
        split participants already fence under it; the others adopt it
        epoch-forward and wake their /shards long-pollers). Best effort:
        an unreachable agent catches up from gossip or its next
        bootstrap — fencing guarantees hold regardless."""

        async def one(gid: str, hostport: str) -> None:
            try:
                await self.rpc.activate(f"{hostport}/{gid}-fabric", smap)
            except Exception as e:
                log.warning("activation push to %s failed: %s", gid, e)

        await asyncio.gather(
            *(one(g, hp) for g, hp in sorted(self.fab.groups.items()))
        )


def _namer(net):
    """Full-address namer through an optional ChaosNet wrap."""
    fn = getattr(net, "local_addr", None)
    if fn is None:
        raise ValueError("meridian roles need a TcpNet-backed transport")
    return fn


def _fleet_secret(cfg) -> bytes:
    """The telemetry-batch HMAC key: its own secret when configured, else
    derived from the intranet secret every fleet process already shares."""
    return (cfg.obs.fleet.secret or cfg.security.abd_mac_secret).encode()


def _identify(cfg, namer, role: str, shard: str = "") -> dict:
    """Stamp this process's identity everywhere satellite views read it:
    the `dds_process_info` gauge on /metrics and the flight recorder's
    incident headers/index (fleet-wide correlation attributes by these)."""
    from dds_tpu.obs.flight import flight
    from dds_tpu.obs.panopticon import process_info

    host = namer("_id").rsplit("/", 1)[0]
    identity = {"host": host, "role": role}
    if shard:
        identity["shard"] = shard
    if cfg.fabric.region:
        identity["region"] = cfg.fabric.region
    flight.configure(identity=identity)
    process_info(role=role, shard=shard, region=cfg.fabric.region)
    return identity


def _start_shipper(cfg, net, namer, stoppables, *, role: str,
                   shard: str = "", slo=None):
    """Wire this process's span shipper at the fleet's collector (no-op
    unless [obs.fleet] is enabled AND names one)."""
    fl = cfg.obs.fleet
    if not (fl.enabled and fl.collector):
        return None
    from dds_tpu.obs.panopticon import SpanShipper

    shipper = SpanShipper(
        net,
        collector=fl.collector,
        secret=_fleet_secret(cfg),
        host=namer("_id").rsplit("/", 1)[0],
        role=role,
        shard=shard,
        region=cfg.fabric.region,
        spool_max=fl.spool_max,
        batch_max=fl.batch_max,
        flush_interval=fl.flush_interval,
        flight_dir=cfg.obs.flight_dir,
        slo=slo,
    )
    shipper.start()
    stoppables.append(_Stopper(shipper.stop))
    return shipper


def _attach_watchtower(cfg, *, check_quorum: bool, geometry: dict) -> None:
    if not cfg.obs.audit_enabled:
        return
    from dds_tpu.obs.watchtower import watchtower
    from dds_tpu.utils.trace import tracer as _tracer

    watchtower.configure(
        quorum_size=cfg.shard.quorum_size,
        n_replicas=cfg.shard.replicas_per_group,
        check_quorum=cfg.obs.audit_quorum_checks and check_quorum,
        group_geometry=geometry,
    )
    watchtower.attach(_tracer)


def _wire_helmsman(cfg, server, stoppables, *, load_census, breaker_census,
                   split, merge, promote, rebalancer, source_ages=None,
                   regions=None):
    """Attach the Helmsman autoscaler to a proxy-resident server when
    [helmsman] is enabled: SLO/admission/breaker signals from the server,
    load shares from the router, actions onto the reshard controller."""
    if not cfg.helmsman.enabled:
        return None
    from dds_tpu.fleet import Helmsman

    admission = server.admission
    hm = Helmsman.from_config(
        cfg.helmsman,
        load_census=load_census,
        slo_alerts=server.slo.alerts,
        shed_level=(lambda a=admission: a.shed_level if a else 0),
        breaker_census=breaker_census,
        source_ages=source_ages,
        split=split,
        merge=merge,
        promote=promote,
        moved_bytes=lambda r=rebalancer: r.moved_bytes_total,
        reshard_busy=lambda r=rebalancer: r.lock.locked(),
        regions=regions,
        # Heliograph: sustained canary unreachability from a region is
        # black-box region_down/promotion evidence — the probes drive the
        # real serving path, so they fire while heartbeats stay green
        canary_unreachable=(lambda s=server: (
            s.heliograph.unreachable_regions()
            if s.heliograph is not None else set()
        )) if cfg.heliograph.enabled else None,
    )
    if admission is not None:
        admission.subscribe(hm.on_admission)
    server.helmsman = hm
    hm.start()
    stoppables.append(_Stopper(hm.stop))
    return hm


async def launch_meridian(cfg, net, stoppables, ssl_server, ssl_client):
    kind, gid = parse_role(cfg.fabric.role)
    if kind == "all":
        return await _launch_all(cfg, net, stoppables, ssl_server, ssl_client)
    if kind == "group":
        return await _launch_group(cfg, net, stoppables, ssl_server,
                                   ssl_client, gid)
    return await _launch_proxy(cfg, net, stoppables, ssl_server, ssl_client)


# --------------------------------------------------------------- role: all


async def _launch_all(cfg, net, stoppables, ssl_server, ssl_client):
    """The whole constellation in this process, over real sockets."""
    from dds_tpu.run import Deployment, proxy_config, shard_configs

    sh = cfg.shard
    rcfg, sup_cfg, abd_cfg = shard_configs(cfg)
    namer = _namer(net)
    const = build_constellation(
        net,
        shard_count=sh.count,
        vnodes_per_group=sh.vnodes_per_group,
        secret=cfg.security.abd_mac_secret.encode(),
        manifest_timeout=sh.manifest_timeout,
        ack_timeout=sh.ack_timeout,
        chunk_keys=sh.migrate_chunk_keys,
        fence_lease=sh.fence_lease,
        journal_dir=sh.plan_dir or None,
        namer=namer,
        n_active=sh.replicas_per_group,
        n_sentinent=sh.sentinent_per_group,
        quorum=sh.quorum_size,
        max_faults=sh.max_faults,
        rcfg=rcfg,
        sup_cfg=sup_cfg,
        abd_cfg=abd_cfg,
        chaos=cfg.attacks.chaos_enabled,
    )
    replicas = {}
    for g in const.groups:
        replicas.update(g.replicas)
    if cfg.recovery.enabled:
        for g in const.groups:
            g.supervisor.start()
    if cfg.recovery.anti_entropy_enabled:
        for node in replicas.values():
            node.antientropy.configure(
                interval=cfg.recovery.anti_entropy_interval,
                jitter=cfg.recovery.anti_entropy_jitter,
            )
            node.antientropy.start()

        class _AES:
            async def stop(self):
                for node in replicas.values():
                    await node.antientropy.stop()

        stoppables.append(_AES())

    # epoch gossip: remote proxies long-poll this process's /shards; every
    # in-process activation (Constellation.split / the admin route) wakes
    # them through the rebalancer's on_activate hook
    hub = EpochGossipHub()
    const.rebalancer.on_activate = lambda smap: hub.notify()
    if sh.plan_dir:
        await const.rebalancer.recover(const.group)
    from dds_tpu.run import ConstellationReshard

    server = DDSRestServer(
        const.router,
        proxy_config(
            cfg, const.groups[0].supervisor.addr, ssl_server, ssl_client,
            reshard_route_enabled=cfg.fabric.admin_routes,
        ),
        local_replicas=replicas,
        slo=SloEngine.from_obs(cfg.obs),
        gossip=hub,
        reshard=ConstellationReshard(const),
    )
    await server.start()
    _wire_helmsman(cfg, server, stoppables,
                   load_census=const.router.load_census,
                   breaker_census=const.router.breaker_census,
                   split=lambda gid, c=const: c.split(gid),
                   merge=lambda gid, c=const: c.merge(gid),
                   promote=lambda gid, c=const: c.promote(gid),
                   rebalancer=const.rebalancer)

    _identify(cfg, namer, "all")
    dep = Deployment(cfg, net, replicas, None, server,
                     const.groups[0].trudy, ssl_client, stoppables,
                     constellation=const)
    # every replica's spans land in THIS process's tracer ring, so
    # the quorum-intersection audit stays sound even over sockets
    _attach_watchtower(
        cfg, check_quorum=True,
        geometry={g.gid: (g.quorum_size, len(g.active))
                  for g in const.groups},
    )
    from dds_tpu.obs.chronoscope import chronoscope

    chronoscope.attach()
    return dep


# ------------------------------------------------------------- role: group


async def _launch_group(cfg, net, stoppables, ssl_server, ssl_client,
                        gid: str):
    """One quorum group + fabric agent + status listener."""
    from dds_tpu.run import Deployment, shard_configs

    sh, fab = cfg.shard, cfg.fabric
    secret = cfg.security.abd_mac_secret.encode()
    rcfg, sup_cfg, abd_cfg = shard_configs(cfg)
    namer = _namer(net)
    if gid not in fab.groups:
        raise ValueError(
            f"this process's group {gid!r} is missing from [fabric.groups]"
        )

    # freshest map available: deterministic epoch-1 from config, upgraded
    # from any reachable peer so a RESTARTED group process re-fences under
    # the fleet's current epoch instead of a stale one
    smap = initial_map(cfg)
    own_status = f"{fab.status_host or cfg.transport.host}:{fab.status_port}"
    peers = [p for p in fab.bootstrap if p != own_status]
    newer, _ = await bootstrap_map(
        peers, secret, timeout=fab.bootstrap_timeout, ssl_context=ssl_client
    )
    if newer is not None and newer.epoch > smap.epoch:
        smap = newer

    state = ShardState(gid, smap, secret)
    geo_kw = {}
    if cfg.geo.enabled and cfg.fabric.region:
        # Atlas on Meridian: a group process is wholly homed in its
        # host's [fabric] region — label its replicas and install the
        # lease table so region-local proxies can hold read leases
        geo_kw = dict(regions=[cfg.fabric.region],
                      home_region=cfg.fabric.region,
                      lease_ttl=cfg.geo.lease_ttl)
    group = build_group(
        net, gid, state,
        n_active=sh.replicas_per_group,
        n_sentinent=sh.sentinent_per_group,
        quorum=sh.quorum_size,
        max_faults=sh.max_faults,
        rcfg=rcfg, sup_cfg=sup_cfg, abd_cfg=abd_cfg,
        chaos=cfg.attacks.chaos_enabled,
        namer=namer,
        **geo_kw,
    )
    if cfg.recovery.enabled:
        group.supervisor.start()
    if cfg.recovery.anti_entropy_enabled:
        for node in group.replicas.values():
            node.antientropy.configure(
                interval=cfg.recovery.anti_entropy_interval,
                jitter=cfg.recovery.anti_entropy_jitter,
            )
            node.antientropy.start()
    stoppables.append(_Stopper(group.stop))

    if cfg.attacks.enabled and cfg.attacks.type == "stale_tag":
        # the cross-host audit regression schedule: this group's replicas
        # answer reads with properly-MAC'd forged stale tags — only the
        # collector-fed Watchtower on the proxy can catch it
        from dds_tpu.malicious.trudy import arm_stale_tag_forgers

        arm_stale_tag_forgers(group.replicas)

    hub = EpochGossipHub()
    view = RemoteShardManager(smap, secret, hub=hub)
    agent = MeridianAgent(net, namer(f"{gid}-fabric"), group, view, secret,
                          hub=hub)
    stoppables.append(_Stopper(agent.stop))

    # stay fresh when the activation push misses us (partition during a
    # reshard we weren't part of): long-poll the other peers' /shards
    follower = MapFollower(
        view, peers, secret, wait=fab.gossip_wait,
        ssl_context=ssl_client, install_also=[state],
    )
    follower.start()
    stoppables.append(_Stopper(follower.stop))

    server = FabricStatusServer(
        fab.status_host or cfg.transport.host, fab.status_port,
        view, lambda: _groups_body(cfg, view.current()), hub,
        group=group, gid=gid, ssl_context=ssl_server,
    )
    await server.start()

    _identify(cfg, namer, f"group:{gid}", shard=gid)
    _start_shipper(cfg, net, namer, stoppables, role=f"group:{gid}",
                   shard=gid)

    # Heliograph on the group role: a standalone prober against the
    # configured [heliograph].targets proxies (a group process has no
    # REST edge of its own to loop back on). Its ledger writes the
    # process-global registry, so the dds_canary_* series ride the span
    # shipper's metrics_text to the proxy's Panopticon rollup — the
    # fleet's `GET /fleet/canary` federates this prober with zero extra
    # wiring, and cross-region target entries give the fleet mutual
    # black-box coverage (group in region A probing the proxy in B).
    if cfg.heliograph.enabled:
        from dds_tpu.clt.canary import parse_canary_targets
        from dds_tpu.obs.heliograph import Heliograph

        targets, bad = parse_canary_targets(cfg.heliograph.targets)
        for entry in bad:
            log.warning("heliograph: skipping malformed target %r", entry)
        if targets:
            wt = None
            if cfg.obs.audit_enabled:
                from dds_tpu.obs.watchtower import watchtower as wt
            helio = Heliograph(cfg.heliograph, targets,
                               watchtower=wt, ssl_context=ssl_client)
            helio.start()
            stoppables.append(_Stopper(helio.stop))

    dep = Deployment(cfg, net, dict(group.replicas), None, server,
                     group.trudy, ssl_client, stoppables)
    # replica spans are local but the coordinators live elsewhere, so the
    # quorum-intersection checks would see every commit as quorumless
    _attach_watchtower(
        cfg, check_quorum=False,
        geometry={gid: (sh.quorum_size, sh.replicas_per_group)},
    )
    # Chronoscope on the raw tracer: this process owns the replica-apply /
    # ingest-queue / h2d stages, and its dds_pipe_* gauges ride the span
    # shipper's metrics_text to the proxy's fleet rollup
    from dds_tpu.obs.chronoscope import chronoscope

    chronoscope.attach()
    return dep


# ------------------------------------------------------------- role: proxy


async def _launch_proxy(cfg, net, stoppables, ssl_server, ssl_client):
    """REST proxy + ShardRouter over remote groups, with map bootstrap,
    epoch-gossip freshness, and the cross-host reshard controller."""
    from dds_tpu.core.quorum_client import AbdClient
    from dds_tpu.run import Deployment, proxy_config, shard_configs

    sh, fab = cfg.shard, cfg.fabric
    secret = cfg.security.abd_mac_secret.encode()
    _, _, abd_cfg = shard_configs(cfg)
    namer = _namer(net)

    smap = initial_map(cfg)
    boot, body = await bootstrap_map(
        fab.bootstrap, secret, timeout=fab.bootstrap_timeout,
        ssl_context=ssl_client,
    )
    state_flag = None
    if boot is not None and boot.epoch >= smap.epoch:
        smap = boot
        state_flag = (body or {}).get("state")

    hub = EpochGossipHub()
    slo_engine = SloEngine.from_obs(cfg.obs)

    # Panopticon: the fleet collector lives with the proxy role — shipped
    # group-process spans stitch onto this process's proxy spans, and the
    # federated /fleet/* views serve from here
    collector = None
    if cfg.obs.fleet.enabled:
        from dds_tpu.obs.panopticon import FleetCollector, NullWatchtower

        collector = FleetCollector(
            net,
            secret=_fleet_secret(cfg),
            host=namer("_id").rsplit("/", 1)[0],
            role="proxy",
            region=cfg.fabric.region,
            stitch_window=cfg.obs.fleet.stitch_window,
            staleness=cfg.obs.fleet.staleness,
            slo=slo_engine,
            # audits off -> stitched traces are sunk, not judged against
            # an unconfigured geometry
            watchtower=None if cfg.obs.audit_enabled else NullWatchtower(),
        )

    def _audit_geometry(m: ShardMap) -> dict:
        return {g: (sh.quorum_size, sh.replicas_per_group) for g in m.groups}

    def make_client(cgid: str) -> AbdClient:
        active, _ = group_endpoints(cfg, cgid)
        hostport = cfg.fabric.groups[cgid]
        c = AbdClient(
            namer(f"{cgid}-proxy"), net, active,
            dataclasses.replace(
                abd_cfg, shard=cgid,
                supervisor=f"{hostport}/{cgid}-supervisor",
            ),
        )
        return c

    def on_install(new_map: ShardMap, old_map: ShardMap) -> None:
        # a split-born group enters the map: grow a client for it from
        # the fabric config (mirrors Constellation.split's wiring)
        for new_gid in new_map.groups:
            if new_gid in router.clients or new_gid not in fab.groups:
                continue
            c = make_client(new_gid)
            c.shard_epoch = lambda m=manager: m.current().epoch
            router.clients[new_gid] = c
            log.info("grew a client for new group %s", new_gid)
        if collector is not None and cfg.obs.audit_enabled:
            # a split-born group must audit against ITS geometry too
            from dds_tpu.obs.watchtower import watchtower

            watchtower.configure(group_geometry=_audit_geometry(new_map))

    manager = RemoteShardManager(smap, secret, hub=hub, on_install=on_install)
    if state_flag:
        manager.install(smap, state=state_flag)
    follower = MapFollower(
        manager, fab.bootstrap, secret, wait=fab.gossip_wait,
        ssl_context=ssl_client,
    )
    clients = {g: make_client(g) for g in smap.groups if g in fab.groups}
    if not clients:
        raise ValueError(
            "no routable groups: [fabric.groups] must map every group id "
            "in the shard map to its transport host:port"
        )
    router = ShardRouter(manager, clients, refresh=follower.poke)
    follower.start()
    stoppables.append(_Stopper(follower.stop))

    rpc = AgentClient(net, namer("meridian-ctl"), timeout=fab.rpc_timeout,
                      budget=fab.rpc_budget or None)
    stoppables.append(_Stopper(rpc.stop))
    controller = MeridianController(cfg, net, namer, manager, rpc)
    if sh.plan_dir:
        # a crashed predecessor may have left a plan mid-flight: resolve
        # it (roll back before commit, forward after) before any traffic
        # or new plan touches the fleet
        await controller.recover()

    sup0 = next(iter(clients.values())).cfg.supervisor
    server = DDSRestServer(
        router,
        proxy_config(
            cfg, sup0, ssl_server, ssl_client,
            reshard_route_enabled=fab.admin_routes,
        ),
        local_replicas={},
        slo=slo_engine,
        gossip=hub,
        reshard=controller,
        fleet=collector,
    )
    await server.start()
    _wire_helmsman(
        cfg, server, stoppables,
        load_census=router.load_census,
        breaker_census=router.breaker_census,
        split=lambda gid, c=controller: c.split(gid),
        merge=lambda gid, c=controller: c.merge(gid),
        promote=lambda gid, c=controller: c.promote(gid),
        rebalancer=controller.rebalancer,
        source_ages=(collector.source_ages if collector is not None
                     else None),
        regions=(collector.source_regions if collector is not None
                 else None),
    )

    _identify(cfg, namer, "proxy")
    dep = Deployment(cfg, net, {}, None, server, None, ssl_client,
                     stoppables)
    if collector is not None:
        collector.start()
        stoppables.append(_Stopper(collector.stop))
        if cfg.obs.audit_enabled:
            # the collector replays STITCHED trace trees — local proxy
            # spans plus the shipped remote replica-handler spans — so
            # the quorum-intersection audits are sound here again. The
            # Watchtower is fed exclusively through the collector (no
            # direct tracer attach: each trace must be audited once,
            # complete).
            from dds_tpu.obs.watchtower import watchtower

            watchtower.configure(
                quorum_size=sh.quorum_size,
                n_replicas=sh.replicas_per_group,
                check_quorum=cfg.obs.audit_quorum_checks,
                group_geometry=_audit_geometry(smap),
            )
        # Chronoscope follows the same once-per-trace rule as the
        # Watchtower: fed exclusively through the collector's stitched
        # replay (detached from the raw tracer), so its critical paths
        # include the remote replica-apply / ingest-queue / h2d spans
        from dds_tpu.obs.chronoscope import chronoscope

        chronoscope.detach()
        collector.profiler = chronoscope
    else:
        # no replica handler spans in this process: tag/repair/state-
        # machine audits stay on, quorum-intersection ones can't be sound
        _attach_watchtower(
            cfg, check_quorum=False,
            geometry=_audit_geometry(smap),
        )
        from dds_tpu.obs.chronoscope import chronoscope

        chronoscope.attach()
    return dep
