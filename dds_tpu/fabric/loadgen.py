"""Meridian load plane: an open-loop, coordinated-omission-safe generator.

The bench driver we had (`clt/client.py`) is CLOSED-loop: each client
waits for its previous response, so a slow server politely slows the
offered load and the measured latencies flatter the system — the classic
coordinated-omission trap. Serving "heavy traffic from millions of
users" is the opposite regime: arrivals keep coming whether or not the
fleet is keeping up. This generator models that:

- **open-loop arrivals** — request start times are drawn from a seeded
  Poisson process at the target rate BEFORE the run begins to matter;
  a request fires at its scheduled instant regardless of how many
  predecessors are still in flight;
- **coordinated-omission-safe latency** — every latency is measured from
  the request's SCHEDULED arrival, not its actual send, so queueing
  delay inside the generator (the symptom of an overloaded server)
  lands in the percentiles instead of silently vanishing. Arrivals that
  cannot even be admitted to the socket pool are recorded as failures at
  the full timeout, never dropped from the sample;
- **Zipf key popularity** (`clt/distribution.ZipfKeys`) over a seeded
  keyset written with the SAME row distribution the closed-loop client
  uses — a handful of hot keys dominate, the tail keeps caches honest;
- **per-class mix** — interactive point ops (GetSet / WriteElement) vs
  aggregate folds (SumAll), matching Bulwark's priority classes;
- **SLO-engine reporting** — every sample feeds an `obs.slo.SloEngine`,
  so a sweep reports burn rates and budget with the same math the
  serving side pages on.

`benchmarks/multihost_load.py` drives this against a multi-process
fleet; tests drive it against an in-process constellation.
"""

from __future__ import annotations

import asyncio
import json
import logging
import math
import random
from dataclasses import dataclass, field

from dds_tpu.clt.distribution import ZipfKeys, random_row
from dds_tpu.http.miniserver import http_request
from dds_tpu.obs.slo import SloEngine
from dds_tpu.utils.tasks import supervised_task

log = logging.getLogger("dds.fabric.loadgen")

# route -> Bulwark priority class (mirrors core/admission's default map)
_CLASS = {"GetSet": "interactive", "WriteElement": "interactive",
          "PutSet": "interactive", "SumAll": "aggregate"}

DEFAULT_MIX = {"GetSet": 0.70, "WriteElement": 0.25, "SumAll": 0.05}


def percentile(sorted_vals: list[float], p: float) -> float:
    """Nearest-rank percentile over an ASCENDING list (0 when empty):
    the smallest value with at least p% of the sample at or below it."""
    if not sorted_vals:
        return 0.0
    k = max(1, math.ceil(p / 100.0 * len(sorted_vals)))
    return sorted_vals[min(k, len(sorted_vals)) - 1]


@dataclass
class LoadReport:
    rate: float                  # offered arrivals/s
    duration: float
    scheduled: int               # arrivals the open loop generated
    completed: int               # responses received (any status)
    good: int                    # 2xx within timeout
    errors: int                  # non-2xx responses
    failures: int                # transport errors / timeouts / shed slots
    achieved_rps: float          # good completions per second
    p50_ms: float
    p95_ms: float
    p99_ms: float
    per_class: dict = field(default_factory=dict)
    slo: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "rate": self.rate, "duration": self.duration,
            "scheduled": self.scheduled, "completed": self.completed,
            "good": self.good, "errors": self.errors,
            "failures": self.failures,
            "achieved_rps": round(self.achieved_rps, 2),
            "p50_ms": round(self.p50_ms, 3),
            "p95_ms": round(self.p95_ms, 3),
            "p99_ms": round(self.p99_ms, 3),
            "per_class": self.per_class,
            "slo": self.slo,
        }


class OpenLoopLoad:
    def __init__(self, targets: list[str], *, keys: int = 64,
                 zipf_s: float = 1.1, mix: dict | None = None,
                 timeout: float = 5.0, seed: int = 0,
                 max_outstanding: int = 2048, ssl_context=None,
                 slo: SloEngine | None = None):
        """`targets` are proxy "host:port" listeners; arrivals spread
        across them round-robin (the multi-proxy front door). One
        instance = one fleet under test; `run()` per rate point."""
        if not targets:
            raise ValueError("open-loop load needs at least one target")
        self.targets = list(targets)
        self.n_keys = keys
        self.zipf_s = zipf_s
        self.mix = dict(mix or DEFAULT_MIX)
        if not self.mix or any(v < 0 for v in self.mix.values()):
            raise ValueError("mix must be non-negative fractions")
        unknown = set(self.mix) - set(_CLASS)
        if unknown:
            raise ValueError(f"unknown mix routes: {sorted(unknown)}")
        self.timeout = timeout
        self._seed = seed
        self.max_outstanding = max_outstanding
        self.ssl_context = ssl_context
        # the SLO engine the sweep reports through — same objectives/
        # windows/burn math as the serving side's /slo
        self.slo = slo or SloEngine()
        self.keys: list[str] = []
        self._zipf: ZipfKeys | None = None
        self._rr = 0

    # ----------------------------------------------------------------- seed

    async def seed(self) -> list[str]:
        """Populate the store: `n_keys` rows from the shared closed-loop
        row distribution (integer lead columns so SumAll folds them),
        keys collected for the Zipf popularity ranking."""
        rng = random.Random(self._seed)
        self.keys = []
        for _ in range(self.n_keys):
            row = random_row(["Int", "Int", "Int"], 5, rng)
            host, port = self._target()
            status, body = await http_request(
                host, port, "POST", "/PutSet",
                json.dumps({"contents": [str(v) for v in row]}).encode(),
                ssl_context=self.ssl_context, timeout=self.timeout * 4,
            )
            if status != 200:
                raise ConnectionError(
                    f"seed PutSet answered {status}: {body[:120]!r}"
                )
            self.keys.append(body.decode())
        self._zipf = ZipfKeys(self.keys, self.zipf_s,
                              random.Random(self._seed + 1))
        return self.keys

    def _target(self) -> tuple[str, int]:
        t = self.targets[self._rr % len(self.targets)]
        self._rr += 1
        host, _, port = t.partition(":")
        return host, int(port)

    # ------------------------------------------------------------------ ops

    def _pick_op(self, rng: random.Random) -> tuple[str, str, str, bytes | None]:
        """(route, method, target-path, body) drawn from the mix."""
        total = sum(self.mix.values())
        u = rng.random() * total
        acc = 0.0
        route = next(iter(self.mix))
        for name, frac in self.mix.items():
            acc += frac
            if u <= acc:
                route = name
                break
        key = self._zipf.pick() if self._zipf is not None else ""
        if route == "GetSet":
            return route, "GET", f"/GetSet/{key}", None
        if route == "WriteElement":
            body = json.dumps({"value": str(rng.randrange(1 << 16))}).encode()
            return route, "PUT", f"/WriteElement/{key}?position=0", body
        if route == "PutSet":
            row = random_row(["Int", "Int", "Int"], 5, rng)
            return route, "POST", "/PutSet", json.dumps(
                {"contents": [str(v) for v in row]}
            ).encode()
        return "SumAll", "GET", "/SumAll?position=0", None

    # ------------------------------------------------------------------ run

    async def run(self, rate: float, duration: float) -> LoadReport:
        """One open-loop rate point. Arrivals are Poisson(`rate`) for
        `duration` seconds; the report's percentiles are over latencies
        measured from each request's scheduled arrival instant."""
        if self._zipf is None:
            await self.seed()
        loop = asyncio.get_running_loop()
        rng = random.Random((self._seed << 16) ^ int(rate * 1000))
        samples: dict[str, list[float]] = {}
        counts = {"good": 0, "errors": 0, "failures": 0, "completed": 0}
        outstanding = 0
        tasks: list[asyncio.Task] = []

        async def one(route: str, method: str, path: str,
                      body, sched: float) -> None:
            nonlocal outstanding
            cls = _CLASS[route]
            host, port = self._target()
            status = 599
            try:
                # per-request budget measured from the SCHEDULED arrival:
                # time already lost queueing inside the generator counts
                # against it, exactly like an impatient user's patience
                budget = max(0.05, self.timeout - (loop.time() - sched))
                status, _ = await http_request(
                    host, port, method, path, body,
                    ssl_context=self.ssl_context, timeout=budget,
                )
                counts["completed"] += 1
                if 200 <= status < 300:
                    counts["good"] += 1
                else:
                    counts["errors"] += 1
            except (OSError, asyncio.TimeoutError, EOFError,
                    ConnectionError, ValueError):
                counts["failures"] += 1
            finally:
                outstanding -= 1
                lat = loop.time() - sched
                samples.setdefault(cls, []).append(lat)
                self.slo.observe(route, status if status < 599 else 503, lat)

        start = loop.time()
        t = 0.0
        scheduled = 0
        while True:
            t += rng.expovariate(rate)
            if t >= duration:
                break
            sched = start + t
            delay = sched - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            scheduled += 1
            route, method, path, body = self._pick_op(rng)
            if outstanding >= self.max_outstanding:
                # the socket pool itself is saturated: an honest sample
                # records the arrival as a full-timeout failure instead
                # of pretending it never happened
                counts["failures"] += 1
                samples.setdefault(_CLASS[route], []).append(self.timeout)
                self.slo.observe(route, 503, self.timeout)
                continue
            outstanding += 1
            tasks.append(supervised_task(
                one(route, method, path, body, sched),
                name=f"loadgen.{route}",
            ))
        if tasks:
            await asyncio.wait(tasks, timeout=self.timeout + 1.0)
        for task in tasks:
            if not task.done():
                task.cancel()
        all_lat = sorted(v for vals in samples.values() for v in vals)
        per_class = {}
        for cls, vals in sorted(samples.items()):
            svals = sorted(vals)
            per_class[cls] = {
                "count": len(svals),
                "p50_ms": round(percentile(svals, 50) * 1e3, 3),
                "p95_ms": round(percentile(svals, 95) * 1e3, 3),
                "p99_ms": round(percentile(svals, 99) * 1e3, 3),
            }
        slo_report = self.slo.report()
        return LoadReport(
            rate=rate, duration=duration, scheduled=scheduled,
            completed=counts["completed"], good=counts["good"],
            errors=counts["errors"], failures=counts["failures"],
            achieved_rps=counts["good"] / duration if duration else 0.0,
            p50_ms=percentile(all_lat, 50) * 1e3,
            p95_ms=percentile(all_lat, 95) * 1e3,
            p99_ms=percentile(all_lat, 99) * 1e3,
            per_class=per_class,
            slo={
                "alerts": self.slo.alerts(),
                "routes": {
                    r: {
                        "burn_rate": d["windows"][
                            f"{int(self.slo.windows[0])}s"]["burn_rate"],
                        "budget_remaining": d["budget_remaining"],
                    }
                    for r, d in slo_report["routes"].items()
                },
            },
        )

    async def sweep(self, rates: list[float],
                    duration: float) -> list[LoadReport]:
        """Arrival-rate sweep, one open-loop run per point (ascending, so
        earlier points warm caches the way a ramping fleet would)."""
        out = []
        for rate in rates:
            out.append(await self.run(rate, duration))
            log.info("rate %.0f/s: good=%d p99=%.1fms", rate,
                     out[-1].good, out[-1].p99_ms)
        return out
