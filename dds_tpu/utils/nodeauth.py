"""Per-node transport credentials: Ed25519 frame signatures.

The reference's intranet rides Akka netty-SSL remoting where every node
presents the shared cluster keystore (`dds-system.conf:18-58`) — peers know
a frame came from *a* cluster member, not from *which* one. Our quorum
protocols key votes by sender (WriteAck / Suspect / TagBatchReply), so the
fabric must bind the claimed `src` to a credential or one compromised
member could stuff quorums with spoofed senders (core/quorum_client.py
documents the hole this closes).

Model: every PROCESS (transport endpoint, "host:port") holds an Ed25519
keypair; a pre-provisioned registry maps each host:port to its public key
(distributed exactly like the TLS certs). TcpNet signs every outbound
frame over (src, dest, payload) and receivers verify the signature against
the registry entry for the claimed src's host:port — a member B forging
src addresses of member A fails verification because it cannot sign with
A's key. Names WITHIN one process are not distinguished (one process, one
trust domain).
"""

from __future__ import annotations

import pathlib

# gated: only the Ed25519 key operations need `cryptography`; the helpers
# below (write_secret_file) serve environments without it, and key users
# fail loudly at first use rather than at import
try:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )

    _CRYPTO_ERR = None
except ModuleNotFoundError as _e:  # pragma: no cover - env-dependent
    Ed25519PrivateKey = Ed25519PublicKey = None
    _CRYPTO_ERR = _e


def _require_crypto() -> None:
    if Ed25519PrivateKey is None:
        raise ModuleNotFoundError(
            "per-node transport identity needs the 'cryptography' package, "
            "which is not installed"
        ) from _CRYPTO_ERR


def generate() -> Ed25519PrivateKey:
    _require_crypto()
    return Ed25519PrivateKey.generate()


def private_hex(key: Ed25519PrivateKey) -> str:
    return key.private_bytes_raw().hex()


def public_hex(key: Ed25519PrivateKey) -> str:
    return key.public_key().public_bytes_raw().hex()


def load_private(hexstr: str) -> Ed25519PrivateKey:
    _require_crypto()
    return Ed25519PrivateKey.from_private_bytes(bytes.fromhex(hexstr.strip()))


def load_public(hexstr: str) -> Ed25519PublicKey:
    _require_crypto()
    return Ed25519PublicKey.from_public_bytes(bytes.fromhex(hexstr.strip()))


def write_secret_file(path: str | pathlib.Path, content: str) -> None:
    """Create a secret file born 0600 (O_EXCL) — never world-readable,
    not even for the instant before a chmod."""
    import os

    p = pathlib.Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    fd = os.open(p, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
    with os.fdopen(fd, "w") as f:
        f.write(content)


def load_or_create(path: str | pathlib.Path) -> Ed25519PrivateKey:
    """Process key from `path` (hex), generated on first use — the dev
    flow; production provisions the file like it provisions TLS keys."""
    p = pathlib.Path(path)
    if p.exists():
        return load_private(p.read_text())
    key = generate()
    write_secret_file(p, private_hex(key))
    return key


def registry(pubkeys: dict[str, str]) -> dict[str, Ed25519PublicKey]:
    """Parse a {host:port -> public key hex} config map."""
    return {hp: load_public(hx) for hp, hx in pubkeys.items()}
