"""Cross-cutting utilities: HMAC signatures, nonces, trust lists, retry, config."""
