"""Record keys, nonces and HMAC signatures.

Counterpart of the reference's `utils/Utils.scala:15-57`: SHA-512 content
hashes for record keys, SecureRandom nonces, and two HMAC families — the
intranet (replica<->replica) "ABD" signature over (value, tag, nonce) and
the proxy<->replica signature over (key[, value], nonce). All comparisons
are constant-time.

Deviations (flagged per SURVEY.md §7):
- The reference's ABD signature covers `tag.seq + 1` instead of `tag.seq`
  (`Utils.scala:33`) — harmless but weird; we sign the actual seq.
- Values are serialized as canonical JSON, not JVM `toString`.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import secrets


def canonical(value) -> str:
    """Deterministic serialization of a JSON-ish value for hashing/signing."""
    return json.dumps(value, sort_keys=True, separators=(",", ":"), default=str)


def key_from_set(contents: list) -> str:
    """SHA-512 content-hash record key (hex, upper) — `Utils.scala:15-18`."""
    return hashlib.sha512(canonical(contents).encode()).hexdigest().upper()


def random_key() -> str:
    """Random SHA-512 record key — `Utils.scala:21-26`."""
    return hashlib.sha512(secrets.token_bytes(100)).hexdigest().upper()


def generate_nonce() -> int:
    return secrets.randbits(63)


def _mac(secret: bytes, content: bytes) -> bytes:
    return hmac.new(secret, content, hashlib.sha256).digest()


def abd_signature(secret: bytes, value, tag, nonce: int) -> bytes:
    """Intranet replica signature over (value, tag, nonce)."""
    content = f"{canonical(value)}|{tag.seq}|{tag.id}|{nonce}".encode()
    return _mac(secret, content)


def validate_abd_signature(secret: bytes, value, tag, nonce: int, given: bytes) -> bool:
    return hmac.compare_digest(abd_signature(secret, value, tag, nonce), given)


def tag_payload(tag):
    """Canonical JSON-safe form of one tag for signing: [seq, id] (None
    stays None). Tags are predictable (seq, coordinator-id), so reply
    MACs must cover them — otherwise an in-transit attacker could swap a
    guessed future tag and later turn the proxy's tag-validated cache
    into a stale serve."""
    return None if tag is None else [tag.seq, tag.id]


def tags_blob(tags) -> bytes:
    """Packed byte form of a tag vector for MACs and fingerprints:
    "seq:len(id):id" fields joined by ";". Both the replica (signer) and
    proxy (verifier) derive this from their own ABDTag objects so
    wire-codec differences can't skew the MAC input. The id is length-
    prefixed because ids originate from wire messages and are never
    charset-checked — without the prefix, delimiter characters inside an
    id would make the packing non-injective and two distinct vectors
    could share one MAC. ~6x cheaper than canonical JSON at K=8192,
    which matters — it sits on the per-aggregate hot path."""
    return ";".join(f"{t.seq}:{len(t.id)}:{t.id}" for t in tags).encode()


def tags_fingerprint(tags) -> bytes:
    """Order-sensitive digest of a tag vector. Equal fingerprints (within
    one key-set request order) mean equal per-key tags — the whole-vector
    freshness check behind the unchanged-reply fast path of ReadTagBatch."""
    return hashlib.sha256(tags_blob(tags)).digest()


def abd_batch_signature(secret: bytes, tags, digest: str, nonce: int) -> bytes:
    """Intranet replica signature over a ReadTagBatch reply (tag vector +
    requested-keys digest + nonce) — the batched analogue of abd_signature."""
    content = tags_blob(tags) + f"|{digest}|{nonce}".encode()
    return _mac(secret, content)


def validate_abd_batch_signature(
    secret: bytes, tags, digest: str, nonce: int, given: bytes
) -> bool:
    return hmac.compare_digest(abd_batch_signature(secret, tags, digest, nonce), given)


def abd_batch_unchanged_signature(
    secret: bytes, fingerprint: bytes, digest: str, nonce: int
) -> bytes:
    """Replica signature over an 'unchanged' ReadTagBatch reply: asserts
    "my tag vector for these keys fingerprints to `fingerprint`" without
    shipping (or re-serializing) the vector."""
    content = b"unchanged|" + fingerprint + f"|{digest}|{nonce}".encode()
    return _mac(secret, content)


def validate_abd_batch_unchanged_signature(
    secret: bytes, fingerprint: bytes, digest: str, nonce: int, given: bytes
) -> bool:
    return hmac.compare_digest(
        abd_batch_unchanged_signature(secret, fingerprint, digest, nonce), given
    )


def value_digest(value) -> str:
    """sha256 hex of a stored set's canonical form — the per-entry content
    commitment behind verified state transfer and Merkle anti-entropy. A
    manifest can attest a repository without shipping values; a seeded
    value is accepted only if it hashes back to the attested digest."""
    return hashlib.sha256(canonical(value).encode()).hexdigest()


def manifest_signature(secret: bytes, signer: str, manifest: dict, nonce: int) -> bytes:
    """Replica signature over its (key -> [seq, id, value-digest]) state
    manifest. Binds the SIGNER address so a relay (the supervisor forwards
    collected manifests to the recovering node) cannot re-attribute one
    replica's manifest to another when distinct signers are counted."""
    content = f"state-digest|{signer}|{canonical(manifest)}|{nonce}".encode()
    return _mac(secret, content)


def validate_manifest_signature(
    secret: bytes, signer: str, manifest: dict, nonce: int, given: bytes
) -> bool:
    return hmac.compare_digest(
        manifest_signature(secret, signer, manifest, nonce), given
    )


def antientropy_signature(secret: bytes, kind: str, payload, nonce: int) -> bytes:
    """Intranet signature over one anti-entropy reply (root / bucket vector /
    key listing). `kind` namespaces the phase so a captured reply of one
    phase cannot be replayed as another's."""
    content = f"ae-{kind}|{canonical(payload)}|{nonce}".encode()
    return _mac(secret, content)


def validate_antientropy_signature(
    secret: bytes, kind: str, payload, nonce: int, given: bytes
) -> bool:
    return hmac.compare_digest(
        antientropy_signature(secret, kind, payload, nonce), given
    )


_NO_VALUE = object()


def proxy_signature(secret: bytes, key: str, nonce: int, value=_NO_VALUE) -> bytes:
    """Proxy<->replica signature; two arities like `Utils.scala:42-49`."""
    if value is _NO_VALUE:
        content = f"{key}|{nonce}".encode()
    else:
        content = f"{key}|{canonical(value)}|{nonce}".encode()
    return _mac(secret, content)


def validate_proxy_signature(secret: bytes, key: str, nonce: int, given: bytes, value=_NO_VALUE) -> bool:
    if value is _NO_VALUE:
        return hmac.compare_digest(proxy_signature(secret, key, nonce), given)
    return hmac.compare_digest(proxy_signature(secret, key, nonce, value), given)
