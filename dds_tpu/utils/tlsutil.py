"""Mutual-TLS plumbing: cert generation + SSLContext builders.

The reference runs mutual TLS on every hop — client↔proxy, proxy↔replica,
replica↔replica — from three JKS keystores with an accept-all hostname
verifier wired globally (SURVEY.md §2.14, §2.20; `dds-system.conf:18-58`,
`dds/http/ssl/DDSInsecureHostnameVerifier.scala:5-6`). Here the same
posture is explicit and configurable: `generate_ca_and_cert` emits a PEM
CA + host cert (the keystore analogue), and the context builders default
to mutual auth with hostname verification OFF (the reference's
cert-CN≠IP workaround) but flippable per config — SURVEY.md §7 says
"reproduce as configurable defaults, not hardcoded insecurity".
"""

from __future__ import annotations

import datetime
import ipaddress
import pathlib
import ssl


def generate_ca_and_cert(
    directory: str | pathlib.Path,
    common_name: str = "dds-node",
    hosts: tuple[str, ...] = ("127.0.0.1", "localhost"),
    days: int = 365,
) -> dict[str, pathlib.Path]:
    """Create ca.pem / cert.pem / key.pem under `directory` (idempotent)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    d = pathlib.Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    paths = {
        "ca": d / "ca.pem",
        "ca_key": d / "ca.key.pem",
        "cert": d / "cert.pem",
        "key": d / "key.pem",
    }
    if all(p.exists() for p in paths.values()):
        return paths

    now = datetime.datetime.now(datetime.timezone.utc)
    ca_key = ec.generate_private_key(ec.SECP256R1())
    ca_name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "dds-ca")])
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(ca_name)
        .issuer_name(ca_name)
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now)
        .not_valid_after(now + datetime.timedelta(days=days))
        .add_extension(x509.BasicConstraints(ca=True, path_length=0), critical=True)
        .sign(ca_key, hashes.SHA256())
    )

    key = ec.generate_private_key(ec.SECP256R1())
    alt_names = []
    for h in hosts:
        try:
            alt_names.append(x509.IPAddress(ipaddress.ip_address(h)))
        except ValueError:
            alt_names.append(x509.DNSName(h))
    cert = (
        x509.CertificateBuilder()
        .subject_name(x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)]))
        .issuer_name(ca_name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now)
        .not_valid_after(now + datetime.timedelta(days=days))
        .add_extension(x509.SubjectAlternativeName(alt_names), critical=False)
        .sign(ca_key, hashes.SHA256())
    )

    pem = serialization.Encoding.PEM
    nokey = serialization.NoEncryption()

    def _write_private(path: pathlib.Path, data: bytes) -> None:
        path.touch(mode=0o600, exist_ok=True)
        path.chmod(0o600)
        path.write_bytes(data)

    paths["ca"].write_bytes(ca_cert.public_bytes(pem))
    _write_private(
        paths["ca_key"],
        ca_key.private_bytes(pem, serialization.PrivateFormat.PKCS8, nokey),
    )
    paths["cert"].write_bytes(cert.public_bytes(pem))
    _write_private(
        paths["key"],
        key.private_bytes(pem, serialization.PrivateFormat.PKCS8, nokey),
    )
    return paths


def server_context(
    cert: str | pathlib.Path,
    key: str | pathlib.Path,
    ca: str | pathlib.Path | None = None,
    require_client_cert: bool = True,
) -> ssl.SSLContext:
    """TLS server context; mutual auth when a CA is given (the default
    posture everywhere in the reference)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    ctx.load_cert_chain(str(cert), str(key))
    if ca is not None:
        ctx.load_verify_locations(str(ca))
        if require_client_cert:
            ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def client_context(
    ca: str | pathlib.Path,
    cert: str | pathlib.Path | None = None,
    key: str | pathlib.Path | None = None,
    verify_hostname: bool = False,
) -> ssl.SSLContext:
    """TLS client context trusting `ca`; presents a client cert when given.

    verify_hostname defaults to False — the reference disables hostname
    verification globally because cert CNs don't match lab IPs
    (`DDSInsecureHostnameVerifier`); we make the same default explicit
    and reversible."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    ctx.load_verify_locations(str(ca))
    ctx.check_hostname = verify_hostname
    if cert is not None and key is not None:
        ctx.load_cert_chain(str(cert), str(key))
    return ctx
