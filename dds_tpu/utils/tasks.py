"""Supervised background tasks: no silent crashes, no GC'd handles.

A bare ``asyncio.ensure_future(coro())`` has two failure modes the
chaos suite cannot see: the event loop keeps only a weak reference to
tasks, so a handle nobody stores can be garbage-collected mid-flight;
and an exception in the coroutine is swallowed until the task object is
finalized, which logs a "Task exception was never retrieved" long after
the actual fault (or never, if the process dies first). Either way a
replica's gossip follower or anti-entropy loop just stops — the
``_key_sync_loop`` class of bug.

``supervised_task`` is the repo-wide discipline (enforced by the Argus
``async.bare-task-spawn`` rule): it retains a strong reference until the
task finishes and attaches a done-callback that logs the crash and cuts
a flight-recorder incident (kind ``task-crash``) at the moment it
happens, with the task's name in the incident. Cancellation is a normal
shutdown path and is not reported.

The returned task is a plain ``asyncio.Task`` — callers keep storing it
and awaiting it on stop exactly as before.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
from typing import Coroutine

log = logging.getLogger("dds.tasks")

# strong refs: the event loop itself only holds weak ones
_TASKS: set[asyncio.Task] = set()


def supervised_task(coro: Coroutine, name: str | None = None) -> asyncio.Task:
    """Spawn `coro` with a retained handle and crash reporting; returns
    the task for callers that also store/await it themselves."""
    # the helper is the one sanctioned spawn point
    task = asyncio.ensure_future(coro)  # argus: ok[async.bare-task-spawn]
    if name:
        task.set_name(name)
    _TASKS.add(task)
    task.add_done_callback(_reap)
    return task


def _reap(task: asyncio.Task) -> None:
    _TASKS.discard(task)
    if task.cancelled():
        return
    exc = task.exception()
    if exc is None:
        return
    name = task.get_name()
    log.error("supervised task %r crashed: %r", name, exc, exc_info=exc)
    try:
        from dds_tpu.obs.flight import flight  # lazy: avoid import cycles

        # sync write is acceptable here: we are already on the fault
        # path, and flight.record rate-limits per kind
        flight.record(  # argus: ok[async.blocking-call]
            "task-crash", task=name, error=repr(exc),
            error_type=type(exc).__name__,
        )
    except Exception:  # reporting must never take down the loop
        log.debug("flight record for task %r failed", name, exc_info=True)


def supervised_count() -> int:
    """Live supervised tasks (tests / shutdown diagnostics)."""
    return len(_TASKS)


async def drain(timeout: float = 5.0) -> None:
    """Cancel and await every live supervised task — a shutdown/test
    helper so no background task outlives its fabric."""
    tasks = [t for t in _TASKS if not t.done()]
    for t in tasks:
        t.cancel()
    if tasks:
        with contextlib.suppress(asyncio.TimeoutError):
            await asyncio.wait_for(
                asyncio.gather(*tasks, return_exceptions=True), timeout)
