"""TimedQueue: the shared bounded hand-off queue with enqueue timestamps.

Lodestone's write-ingest queue, Spyglass's index-ingest queue, and the
proxy fold coalescer all share one shape: the request path appends work,
a debounced worker drains it in batches. Before this helper each kept a
bare list/dict, so queue AGE — how long entries sat before the drain —
was invisible (Chronoscope's ingest-queue-wait stage had nothing to
attribute), and drops were counted ad-hoc (Lodestone dropped pool-less
entries silently). TimedQueue stamps every entry at enqueue, measures
wait at drain, counts every discarded entry under a `reason` label, and
exports a uniform gauge family:

    dds_queue_depth{queue}                current entries
    dds_queue_oldest_age_seconds{queue}   age of the head entry
    dds_queue_dropped_total{queue,reason} cumulative discards (counter,
                                          incremented at drop time)
    dds_queue_wait_seconds{queue}         drain-time wait histogram

Drains additionally record an `ingest.queue_wait` span (duration = the
longest wait in the batch) so the wait shows up in trace waterfalls when
a drain happens to run under an active trace context; off-trace drains
record the span unlinked, which still feeds `tracer.summary()`.

`maxlen=None` means unbounded (the fold coalescer: entries carry
futures, so rejecting them is not a drop but an error — the caller owns
that policy). Bounded queues reject at `offer` time with reason="full".
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Iterable, Optional

from dds_tpu.obs import context as obs_context
from dds_tpu.obs.metrics import metrics
from dds_tpu.utils.trace import tracer

_WAIT_BUCKETS = (0.001, 0.005, 0.02, 0.1, 0.5, 2.0)


class TimedQueue:
    """Thread-safe FIFO of (enqueue_ts, item) with drop accounting."""

    def __init__(self, name: str, maxlen: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic,
                 registry=metrics):
        self.name = name
        self.maxlen = None if maxlen is None else int(maxlen)
        self._clock = clock
        self._registry = registry
        self._lock = threading.Lock()
        self._entries: collections.deque = collections.deque()
        self._offered = 0
        self._drained = 0
        self._dropped: collections.Counter = collections.Counter()

    # -------------------------------------------------------------- enqueue

    def offer(self, item: Any) -> bool:
        """Append one entry; False = queue full (counted reason="full")."""
        now = self._clock()
        with self._lock:
            if self.maxlen is not None and len(self._entries) >= self.maxlen:
                self._dropped["full"] += 1
                full = True
            else:
                self._entries.append((now, item))
                self._offered += 1
                full = False
        if full:
            self._count_drop("full", 1)
        return not full

    def offer_many(self, items: Iterable[Any]) -> int:
        """Append entries until full; returns how many were accepted (the
        remainder are counted as reason="full" drops)."""
        items = list(items)
        if not items:
            return 0
        now = self._clock()
        with self._lock:
            if self.maxlen is None:
                room = len(items)
            else:
                room = max(0, self.maxlen - len(self._entries))
            take = items[:room]
            for item in take:
                self._entries.append((now, item))
            self._offered += len(take)
            rejected = len(items) - len(take)
            if rejected:
                self._dropped["full"] += rejected
        if rejected:
            self._count_drop("full", rejected)
        return len(take)

    def drop(self, n: int = 1, *, reason: str) -> None:
        """Account entries discarded for an external reason (e.g.
        Lodestone's pool-less writes, reason="no_pool") WITHOUT them ever
        entering the queue — the silent-drop fix."""
        if n <= 0:
            return
        with self._lock:
            self._dropped[reason] += n
        self._count_drop(reason, n)

    # ---------------------------------------------------------------- drain

    def drain(self) -> list:
        """Swap-and-drain every queued item (oldest first), recording the
        batch's queue-wait telemetry. Returns the bare items."""
        return [item for _, item in self.drain_entries()]

    def drain_entries(self) -> list[tuple[float, Any]]:
        """Like `drain` but returns (wait_seconds, item) pairs so callers
        that need per-entry waits (the fold coalescer's per-waiter spans)
        can attribute them individually."""
        now = self._clock()
        with self._lock:
            if not self._entries:
                return []
            entries, self._entries = self._entries, collections.deque()
            self._drained += len(entries)
        out = [(max(0.0, now - ts), item) for ts, item in entries]
        self._record_wait(out)
        return out

    def clear(self, *, reason: Optional[str] = None) -> int:
        """Discard everything queued; with `reason` the discards count as
        drops (Spyglass invalidation), without it they simply vanish."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            if reason is not None and n:
                self._dropped[reason] += n
        if reason is not None and n:
            self._count_drop(reason, n)
        return n

    # ------------------------------------------------------------ telemetry

    def _count_drop(self, reason: str, n: int) -> None:
        try:
            self._registry.inc("dds_queue_dropped_total", n,
                               queue=self.name, reason=reason,
                               help="entries discarded per queue and reason")
        except Exception:  # noqa: BLE001 — telemetry never breaks the queue
            pass

    def _record_wait(self, entries: list[tuple[float, Any]]) -> None:
        oldest = max(w for w, _ in entries)
        try:
            self._registry.observe("dds_queue_wait_seconds", oldest,
                                   buckets=_WAIT_BUCKETS, queue=self.name)
        except Exception:  # noqa: BLE001
            pass
        cur = obs_context.current()
        tracer.record(
            "ingest.queue_wait", oldest * 1e3,
            _ctx=obs_context.child(cur) if cur is not None else None,
            queue=self.name, n=len(entries),
        )

    # -------------------------------------------------------------- surface

    def depth(self) -> int:
        with self._lock:
            return len(self._entries)

    def oldest_age(self) -> float:
        """Seconds the head entry has been waiting (0.0 when empty)."""
        with self._lock:
            if not self._entries:
                return 0.0
            head_ts = self._entries[0][0]
        return max(0.0, self._clock() - head_ts)

    def dropped(self, reason: Optional[str] = None) -> int:
        with self._lock:
            if reason is not None:
                return self._dropped.get(reason, 0)
            return sum(self._dropped.values())

    def stats(self) -> dict:
        with self._lock:
            return {
                "depth": len(self._entries),
                "offered": self._offered,
                "drained": self._drained,
                "dropped": dict(self._dropped),
            }

    def export_gauges(self, registry=metrics) -> None:
        registry.set("dds_queue_depth", self.depth(), queue=self.name,
                     help="current entries per hand-off queue")
        registry.set("dds_queue_oldest_age_seconds",
                     round(self.oldest_age(), 6), queue=self.name,
                     help="age of the oldest queued entry per queue")
