"""Typed configuration: one dataclass tree, loadable from TOML or JSON.

Replaces the reference's two HOCON files (`dds-system.conf`, `client.conf`)
with the same parameter catalog — topology with sentinent flags, quorum
sizes, proactive-recovery timers, proxy/key-sync settings, MAC secrets,
workload proportions, column schema, attack simulation — as explicit typed
fields (SURVEY.md §5.6).
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
from dataclasses import dataclass, field


@dataclass
class ReplicaTopology:
    endpoints: list[str] = field(
        default_factory=lambda: [f"replica-{i}" for i in range(9)]
    )
    sentinent: list[str] = field(
        default_factory=lambda: ["replica-7", "replica-8"]
    )
    byz_quorum_size: int = 5           # dds-system.conf:131
    byz_max_faults: int = 2            # dds-system.conf:132
    # Multi-host topology (transport.kind = "tcp" only), mirroring the
    # reference's per-host endpoint URIs + `replicas.local` split
    # (`dds-system.conf:113-128`, `Main.scala:90-99`):
    # - addresses: replica name -> "host:port" of the process hosting it;
    #   unmapped names default to this process's transport address.
    # - local: names THIS process instantiates (empty = every name whose
    #   address resolves to this process).
    # - supervisor_address: "host:port" of the process running the
    #   supervisor (empty = this process).
    addresses: dict = field(default_factory=dict)
    local: list[str] = field(default_factory=list)
    supervisor_address: str = ""


@dataclass
class SecurityConfig:
    abd_mac_secret: str = "intranet-abd-secret"
    proxy_mac_secret: str = "rest2abd"          # dds-system.conf:94 default
    nonce_challenge_increment: int = 1
    transport_frame_secret: str = ""            # empty -> unauthenticated frames
    # mutual TLS on the HTTP hops (certificates/ JKS analogue, SURVEY §2.20).
    # Multi-host deployments MUST pre-provision one shared CA and per-host
    # certs via tls_ca/tls_cert/tls_key; when those are empty a per-node
    # dev CA auto-generates under tls_dir (single-host only — two nodes
    # with independent CAs cannot verify each other).
    tls_enabled: bool = False
    # mutual TLS on the replica/supervisor TCP fabric (the reference's
    # netty-SSL intranet, dds-system.conf:18-58). Shares the tls_* material
    # below; only meaningful with transport.kind = "tcp".
    intranet_tls_enabled: bool = False
    tls_dir: str = "certs"
    tls_ca: str = ""
    tls_cert: str = ""
    tls_key: str = ""
    tls_verify_hostname: bool = False  # reference's accept-all verifier default
    # Per-node transport identity (utils/nodeauth, tcp transport only):
    # binds every frame's claimed src to the sending PROCESS's Ed25519 key,
    # so one compromised member cannot spoof another's sender-keyed quorum
    # votes (WriteAck / Suspect / TagBatchReply). node_key_path holds this
    # process's private key (hex; auto-generated if missing);
    # node_public_keys maps every "host:port" to its public key hex,
    # provisioned like the TLS certs. Enabled when node_public_keys is
    # non-empty.
    node_key_path: str = ""
    node_public_keys: dict = field(default_factory=dict)


@dataclass
class RecoveryConfig:
    enabled: bool = True
    warm_up: float = 5.0               # dds-system.conf:137
    interval: float = 7.0              # dds-system.conf:138
    sentinent_awake_timeout: float = 5.0
    crashed_recovery_timeout: float = 12.0
    # optional snapshot-to-disk (SURVEY §5.4: replication stays the source
    # of truth; snapshots only warm cold starts). 0 disables.
    snapshot_dir: str = ""
    snapshot_interval: float = 0.0
    # snapshot v2 (core/snapshot.py): generations kept per replica, and an
    # optional explicit MAC-key base for the authenticated footer (empty =
    # derived from security.abd_mac_secret + the node key file when
    # security.node_key_path is provisioned)
    snapshot_keep: int = 3
    snapshot_secret: str = ""
    # Aegis verified state transfer (core/supervisor.py): recovery seeds
    # are cross-checked against a quorum of HMAC-signed state manifests;
    # the recovering node accepts only entries attested by >= f+1 distinct
    # signers. Off = the reference's single-spare trust.
    verified_transfer: bool = True
    manifest_timeout: float = 2.0
    state_chunk_keys: int = 256
    # Merkle anti-entropy (core/antientropy.py): every local replica runs
    # a background pull loop on a jittered timer, so healed partitions,
    # snapshot-restored rejoiners, and post-reseed holes converge without
    # waiting for client reads
    anti_entropy_enabled: bool = True
    anti_entropy_interval: float = 5.0
    anti_entropy_jitter: float = 2.0


@dataclass
class ProxySettings:
    host: str = "127.0.0.1"
    port: int = 8443
    crypto_backend: str = "cpu"        # the BASELINE.json crypto.backend switch
    intranet_request_timeout: float = 5.0
    # deadline-propagated retry (utils/retry; see http/server.ProxyConfig):
    # one request_budget per REST request, exponential backoff + full
    # jitter from retry_backoff up to retry_max_delay; retry_attempts > 0
    # adds a hard attempt cap (0 = deadline-governed); exhaustion returns
    # 503 with Retry-After = retry_after_hint seconds
    request_budget: float = 8.0
    retry_attempts: int = 0
    retry_backoff: float = 0.3
    retry_max_delay: float = 2.0
    retry_after_hint: float = 1.0
    handler_timeout: float = 0.0       # miniserver backstop, 0 = off
    # per-coordinator circuit breaker (transient-failure steering that
    # self-heals after breaker_reset seconds via a half-open probe)
    breaker_threshold: int = 3
    breaker_reset: float = 2.0
    key_sync_enabled: bool = False
    key_sync_warm_up: float = 1.0
    key_sync_interval: float = 5.0
    remote_peers: list[str] = field(default_factory=list)
    # stored_keys snapshot file (empty = in-memory only, the reference's
    # lossy behavior); restarted proxies also pull keys from remote_peers
    # at start when key_sync_enabled
    stored_keys_path: str = ""
    # gather window (s) for coalescing concurrent small aggregate folds
    # into one device dispatch; 0 disables
    coalesce_window: float = 0.002


@dataclass
class TransportConfig:
    kind: str = "memory"               # memory | tcp
    host: str = "127.0.0.1"
    port: int = 2552
    # Peer-visible address of this process ("host" or "host:port"); set it
    # whenever the bind address differs from how peers name this process
    # (0.0.0.0 binds, NAT, hostname-vs-IP). Empty = bind address. With
    # per-node identity enabled, launch() fails fast if the advertised
    # address is missing from security.node_public_keys — peers could
    # never verify this process's frames.
    advertise: str = ""


@dataclass
class DataTableConfig:
    max_nr_of_columns: int = 16
    fixed_nr_of_columns: int = 8
    fixed_columns_mappings: list[str] = field(
        default_factory=lambda: ["Int", "String", "Int", "Int", "String", "String", "String", "Blob"]
    )
    fixed_columns_hcrypt: list[str] = field(
        default_factory=lambda: ["OPE", "CHE", "PSSE", "MSE", "CHE", "CHE", "CHE", "None"]
    )


@dataclass
class ClientSettings:
    nr_of_local_clients: int = 1
    nr_of_operations: int = 100
    failed_contact_attempts_threshold: int = 3
    http_requests_timeout: float = 10.0
    proportions: dict = field(default_factory=dict)   # op name -> fraction
    data_table: DataTableConfig = field(default_factory=DataTableConfig)
    paillier_bits: int = 2048
    rsa_bits: int = 1024
    # HE key persistence (client.conf:81-88 ships serialized keys so runs
    # are reproducible against existing data; same contract, sane format):
    # - he_keys_path: load HEKeys JSON from this file if it exists; after
    #   generating fresh keys, save them there so the next run (fresh
    #   process) can decrypt yesterday's store.
    # - he_keys_inline: a full HEKeys JSON blob directly in the config
    #   (wins over the path when set).
    he_keys_path: str = ""
    he_keys_inline: str = ""
    # PSSE encryption obfuscators: True = DJN short-exponent blinding
    # (models/paillier.py blind_fast — ~5x cheaper per ciphertext, rests on
    # the DJN subgroup assumption), False = textbook full-width r^n.
    fast_blinding: bool = True
    # route bulk client-side encryption (workload PutSet rows) through this
    # CryptoBackend's batched modexp ("tpu" | "native"; empty = host per-op
    # DJN path). Above the batch threshold one device dispatch precomputes
    # every full-width obfuscator a digest needs.
    bulk_encrypt_backend: str = ""


@dataclass
class FleetObsConfig:
    """Panopticon fleet observability plane (dds_tpu/obs/panopticon):
    every non-proxy Meridian process ships completed span trees, flight
    incidents, and metric/SLO snapshots to the proxy-role collector over
    the existing TcpNet fabric; the collector stitches cross-host traces
    back into single trees for the Watchtower (re-arming quorum audits on
    multi-host splits), federates /fleet/metrics and /fleet/slo, and
    correlates incidents fleet-wide at /fleet/incidents. DEPLOY.md
    "Fleet observability (Panopticon)" is the runbook."""

    enabled: bool = False
    # collector transport "host:port" (the PROXY process's [transport]
    # bind). Empty on the proxy role itself — the collector listens on
    # the process's own TcpNet under the "panopticon" endpoint name.
    collector: str = ""
    # telemetry-batch HMAC secret; empty = derive from
    # security.abd-mac-secret (telemetry is integrity-checked, but a
    # Byzantine host can still lie about its OWN stats — see DEPLOY.md)
    secret: str = ""
    # shipper spool bound (completed span TREES, not spans). Overflow
    # drops the oldest tree and increments
    # dds_fleet_ship_dropped_total{reason="spool_overflow"} — the request
    # path is never blocked by telemetry.
    spool_max: int = 256
    # max span trees per shipped batch and the flush-loop period
    batch_max: int = 32
    flush_interval: float = 0.25
    # how long the collector holds a locally-completed root span before
    # replaying the stitched tree into the Watchtower (remote handler
    # spans must cross a socket + one flush interval to arrive)
    stitch_window: float = 1.0
    # a federated source whose last batch is older than this is marked
    # stale in /fleet/metrics and /fleet/slo (0 disables marking)
    staleness: float = 10.0


@dataclass
class ObsConfig:
    """Telescope (dds_tpu/obs) wiring. Env-flag twins exist for harnesses
    that cannot pass a config: DDS_OBS_FLIGHT_DIR / DDS_OBS_FLIGHT_MAX /
    DDS_OBS_FLIGHT_INTERVAL (flight recorder), DDS_OBS_RING /
    DDS_OBS_TRACE (tracer ring size / kill switch)."""

    # GET /metrics (Prometheus text). On by default — aggregated series,
    # the scrape plane production monitoring expects.
    metrics_route: bool = True
    # GET /_trace (per-span stats; reveals workload shape). `debug = true`
    # also enables it, preserving the old behavior.
    trace_route: bool = False
    # flight recorder: directory for fault-triggered JSONL incident dumps
    # (empty = disabled unless DDS_OBS_FLIGHT_DIR is set)
    flight_dir: str = ""
    flight_max_incidents: int = 32
    # min seconds between incidents of the same kind (a flapping breaker
    # must not fill a disk)
    flight_min_interval: float = 1.0
    # Watchtower online BFT invariant auditor (obs/watchtower): subscribes
    # to completed traces and checks quorum intersection, per-key tag
    # monotonicity, read-sees-latest, anti-entropy repair convergence, and
    # breaker/suspicion state-machine legality; violations become
    # dds_audit_violations_total + flight incidents, never exceptions.
    audit_enabled: bool = True
    # quorum-intersection checks need every replica's handler spans in
    # THIS process's tracer ring; launch() additionally disables them when
    # the topology splits replicas across hosts
    audit_quorum_checks: bool = True
    # SLO engine (obs/slo): per-route latency objectives + error-budget
    # burn-rate windows, served at GET /slo and as dds_slo_* gauges.
    # Default: objective of requests per route answer < latency-ms without
    # a 5xx; per-route overrides under [obs.slo-routes.<Route>].
    slo_route: bool = True
    slo_objective: float = 0.99
    slo_latency_ms: float = 250.0
    slo_fast_window: float = 300.0
    slo_slow_window: float = 3600.0
    # page signal: both windows burning error budget at >= this multiple
    # of the sustainable rate (14.4x = a 30-day budget gone in ~2 days)
    slo_burn_alert: float = 14.4
    # route name -> {"objective": float, "latency-ms": float}
    slo_routes: dict = field(default_factory=dict)
    # Panopticon fleet plane ([obs.fleet] in TOML)
    fleet: FleetObsConfig = field(default_factory=FleetObsConfig)


@dataclass
class ShardConfig:
    """Constellation sharding plane (dds_tpu/shard): partition the
    keyspace across `count` independent BFT-ABD quorum groups, each with
    its own replicas, spares, supervisor, anti-entropy loop, and attack
    surface. Point ops route to one group; SumAll/MultAll scatter-gather
    per-shard folds. With `transport.kind = "memory"` the whole
    constellation lives in one process; with `"tcp"` the Meridian plane
    ([fabric] section) spreads groups and proxies across OS processes,
    distributing the signed map via GET /shards + epoch gossip
    (DEPLOY.md "Sharding" and "Multi-host (Meridian)")."""

    enabled: bool = False
    count: int = 2
    # consistent-hash ring positions contributed per group; more vnodes =
    # smoother key balance, marginally slower owner lookups
    vnodes_per_group: int = 16
    # per-group geometry (groups are homogeneous; n = active + spares)
    replicas_per_group: int = 4
    sentinent_per_group: int = 1
    quorum_size: int = 3               # 2f+1 at f=1 for the default 4
    max_faults: int = 1
    # live resharding (shard/rebalance): migration stream chunking and
    # the attestation/ack collection timeouts
    migrate_chunk_keys: int = 256
    manifest_timeout: float = 2.0
    ack_timeout: float = 5.0
    # fence-lease TTL (seconds) for a reshard's freeze installs: a plan
    # whose driver crashes before commit heals back to the committed map
    # when the lease expires, so no group stays fenced forever. 0 keeps
    # the legacy forever-fenced-until-next-install behavior. Size it
    # comfortably above freeze->commit under load (attest + stream +
    # one ack timeout)
    fence_lease: float = 30.0
    # directory for the crash-safe reshard plan journal (empty = keep
    # plan state in memory only — fine for tests and ephemeral fleets,
    # but a restarted driver then cannot resolve an interrupted plan)
    plan_dir: str = ""


@dataclass
class AnalyticsConfig:
    """Prism encrypted-analytics plane (dds_tpu/analytics): plaintext-
    matrix x Paillier-ciphertext-vector products served as REST routes
    (POST /MatVec, /WeightedSum, /GroupBySum). The proxy sees ciphertexts
    and the client's PLAINTEXT weights — public parameters only, never
    keys; DEPLOY.md "Encrypted analytics" documents the boundary. Note the
    weights themselves are visible to the proxy: a deployment whose query
    matrix is sensitive should not use these routes."""

    enabled: bool = True
    # per-request weight-row / group cap (bounds kernel work one request
    # can demand; the DDS_ANALYTICS_MAX_ROWS env knob overrides, both
    # validated by ops/flags.analytics_max_rows)
    max_rows: int = 256
    # request-body byte cap for the analytics routes (413 beyond; 0 = off)
    max_request_bytes: int = 1048576


@dataclass
class ResidentConfig:
    """Lodestone device-resident ciphertext plane (dds_tpu/resident):
    per-shard-group content-addressed limb pools pinned in device memory,
    write-path incremental ingest, and single-dispatch fused sharded
    aggregates. HBM budget per group is rows x L x 4 bytes (L = limbs of
    the aggregate modulus: 256 for 2048-bit Paillier n^2 -> 1 KiB/row);
    past `max-rows` a pool resets and re-ingests on demand — never wrong
    results, only a re-paid one-time ingest. DEPLOY.md "Resident
    ciphertext plane (Lodestone)" is the runbook."""

    enabled: bool = False
    # per-pool capacity: start here, double up to max-rows, then reset
    initial_rows: int = 256
    max_rows: int = 65536
    # smallest total aggregate width routed through the fused resident
    # fold; 0 = the backend's own device crossover decides (a cpu-backend
    # proxy with 0 sends every modular aggregate through the plane)
    min_fold: int = 0
    # write-path ingest: committed PutSet/AddElement/WriteElement
    # ciphertexts ingest into this group's existing pools OFF the
    # request's critical path, coalesced in ingest-window seconds — a
    # warm fleet's first post-write aggregate pays zero ingest
    write_ingest: bool = True
    ingest_window: float = 0.005


@dataclass
class StorageConfig:
    """Stratum tiered ciphertext storage (dds_tpu/storage): grows the
    Lodestone resident plane downward into a three-tier hierarchy — HBM
    pools (hot), a host-pinned numpy limb cache (warm), and an append-only
    HMAC'd segment log on disk (cold, snapshot-v2 crash-safety). Pool
    capacity overflow then EVICTS coldest-first instead of resetting, and
    aggregates split into a resident-fused leg plus streamed-from-tier
    legs merged bit-for-bit exactly. Requires `[resident]` enabled (the
    hot tier IS the resident plane). Budgeting arithmetic and the
    crash-recovery matrix live in DEPLOY.md "Tiered storage (Stratum)"."""

    enabled: bool = False
    # segment + manifest directory (created on first demotion/boot)
    dir: str = "./stratum"
    # warm-tier host budget: rows are L x 4 bytes (1 KiB at L=256), so
    # 64 MiB holds ~65k demoted rows — one full default pool over again
    warm_bytes: int = 64 << 20
    # streamed-fold slice: rows per host->HBM transfer + device fold
    chunk_rows: int = 256
    # promotion: decayed touch score a warm/cold entry must clear to
    # re-enter HBM, and the per-fold promotion cap (anti-thrash)
    promote_score: float = 2.0
    max_promote: int = 256
    # popularity decay half-life (seconds) for the tier directory's EWMA
    half_life: float = 60.0
    # manifest generations kept (the snapshot keep-N discipline) and the
    # live-segment count that triggers compaction
    keep: int = 3
    compact_segments: int = 8


@dataclass
class SearchConfig:
    """Spyglass device-resident encrypted search plane (dds_tpu/search):
    per-shard-group, per-column indexes over the DET (equality) and OPE
    (order/range) column families, validated per query with ONE batched
    tag round and evaluated with the ops/predicate kernels. Off = every
    Search*/Order*/Range request takes the legacy full-keyspace scan.
    DEPLOY.md "Encrypted search (Spyglass)" is the runbook."""

    enabled: bool = False
    # write-path ingest (the Lodestone pattern): committed writes queue
    # their (tag, value) for index upsert OFF the request path, coalesced
    # in ingest-window seconds; max-pending bounds the queue — overflowed
    # keys simply read as stale at the next query and are repaired
    write_ingest: bool = True
    ingest_window: float = 0.005
    max_pending: int = 8192


@dataclass
class AdmissionConfig:
    """Bulwark overload control (dds_tpu/core/admission): per-tenant/
    per-priority-class token buckets and SLO-burn-driven load shedding at
    the REST edge, decided BEFORE a Deadline is minted — rejected
    requests answer 429/503 in microseconds with a Retry-After derived
    from actual refill/breaker state. Priority classes: `interactive`
    (point ops) > `aggregate` (folds/search/analytics) > `background`
    (gossip, unclassified); the shedding ratchet drops the lowest class
    first and recovers one level per `shed-hold` clean evaluations.
    DEPLOY.md "Overload control (Bulwark)" is the runbook."""

    enabled: bool = False
    # tenant attribution header; absent header = the "default" tenant
    tenant_header: str = "x-dds-tenant"
    # per-tenant token buckets, one per priority class: `rate` sustained
    # requests/s refilling up to `burst` capacity. Sized so a single
    # well-behaved tenant never notices them; the point is that ONE hot
    # tenant exhausts its own bucket, not the fleet's Deadline budgets.
    interactive_rate: float = 400.0
    interactive_burst: float = 800.0
    aggregate_rate: float = 64.0
    aggregate_burst: float = 128.0
    background_rate: float = 16.0
    background_burst: float = 32.0
    # route name -> class name overrides (e.g. { "SearchEq" = "background" })
    classes: dict = field(default_factory=dict)
    # shedding controller: evaluated every eval-interval seconds (and
    # lazily under traffic); distress = any SERVED class's multiwindow SLO
    # burn alert firing, or >= breaker-shed-fraction of trusted
    # coordinators refusing traffic. Recovery steps down ONE level after
    # shed-hold consecutive clean evaluations (hysteresis).
    eval_interval: float = 1.0
    shed_hold: int = 3
    # 1 sheds background, 2 also aggregates, 3 also interactive (a full
    # shed: only the exempt /health /metrics /slo /shards keep answering).
    # Default stops at 2 — interactive traffic is never shed unless an
    # operator explicitly allows it.
    max_shed_level: int = 2
    breaker_shed_fraction: float = 0.5
    # storage-layer fast-fail (AbdClient): when ALL of a group's
    # coordinators have open breakers and none will half-open within the
    # remaining budget, degrade instantly instead of burning the Deadline
    fast_fail: bool = True
    # adaptive fold coalescing: size proxy.coalesce-window from the
    # observed fold arrival rate — stretch toward coalesce-max-window
    # until ~coalesce-target-folds arrivals share a dispatch under load,
    # snap back to the base window when idle
    adaptive_coalesce: bool = True
    coalesce_max_window: float = 0.02
    coalesce_target_folds: float = 8.0


@dataclass
class TenancyConfig:
    """Bastion multi-tenant isolation (per-tenant crypto domains +
    blast-radius containment). The `x-dds-tenant` header is ALWAYS
    validated at the REST edge (charset/length clamp, typed 400 on
    garbage, absent = "default"); `enabled = true` additionally turns on
    keyspace ownership enforcement (typed 403 on cross-tenant key
    access), tenant-striped Lodestone pools and Spyglass indexes,
    per-tenant SLO/usage attribution, and weighted-fair admission with
    per-tenant burn-driven shedding. DEPLOY.md "Multi-tenancy (Bastion)"
    is the runbook."""

    enabled: bool = False
    # tracked-tenant cardinality bound shared by admission state, SLO
    # attribution, and the keyring; tenants beyond it fold into an
    # "overflow" aggregate (requests still serve — only attribution
    # coarsens)
    max_tenants: int = 1024
    # weighted-fair admission: tenant id -> relative weight; unlisted
    # tenants get default-weight. Under class overload each tenant's
    # bucket refill contracts to its weight share of the class rate.
    weights: dict = field(default_factory=dict)
    default_weight: float = 1.0
    # per-tenant burn-driven shedding: a tenant whose bad-outcome share
    # exceeds burn-threshold of the distress window is shed by itself
    # (429s for its sheddable classes) for at least shed-hold clean
    # evaluations, instead of ratcheting the whole fleet
    burn_threshold: float = 0.5
    shed_hold: int = 3
    # key lifecycle: rotation grace window (seconds) during which a
    # rotated-out epoch still decrypts (re-encrypt-on-read); key family
    # sizes for lazily-generated tenant keyrings
    rotation_grace: float = 300.0
    paillier_bits: int = 2048
    rsa_bits: int = 1024
    # per-family metric series cap applied to the process registry
    # (obs/metrics cardinality guard)
    metrics_max_series: int = 1024


@dataclass
class CryptoConfig:
    """Sanctum secret-material execution plane (dds_tpu/sanctum): where
    computation that TOUCHES private-key material runs — today the CRT
    legs of batched Paillier decryption (client-side verification and
    `HomoProvider.decrypt_rows`). Host-only by default. `secret-device =
    true` is the explicit opt-in that fuses both CRT legs into one
    batched device dispatch: faster bulk decryption, in exchange for
    transient HBM residency of p^2/q^2-derived values (executables stay
    secret-free — constants ride as traced arguments — and the
    persistent compile cache is bypassed for those compiles). The
    DDS_SECRET_DEVICE env twin overrides; both are validated loudly by
    ops/flags.secret_device. DEPLOY.md "Secret-material trust boundary
    (Sanctum)" is the runbook."""

    secret_device: bool = False


@dataclass
class FabricConfig:
    """Meridian multi-host shard fabric (dds_tpu/fabric): spread a
    Constellation's S quorum groups plus separate proxies across N OS
    processes/hosts over `TcpNet`, from one shared TOML that differs per
    process only in `role` (and transport bind). Active with
    `shard.enabled = true` + `transport.kind = "tcp"`.

    Roles:
    - `"all"`    — the whole constellation (groups + router + REST proxy)
                   in this process, over real sockets;
    - `"group:N"`— only quorum group sN (replicas, spares, supervisor,
                   anti-entropy, Trudy) plus its fabric agent and a
                   status listener serving the signed map at GET /shards;
    - `"proxy"`  — the REST proxy + ShardRouter: bootstraps the shard map
                   from `bootstrap` peers' signed GET /shards, stays
                   fresh via epoch-gossip long-polls, and hosts the
                   reshard controller (POST /_reshard when
                   `admin-routes`).

    `groups` maps every group id (including standby split targets not yet
    in the map) to the TRANSPORT "host:port" of its owning process;
    replica/supervisor/agent endpoint addresses derive from it plus the
    homogeneous [shard] geometry, identically in every process.
    DEPLOY.md "Multi-host (Meridian)" is the runbook."""

    role: str = "all"
    groups: dict = field(default_factory=dict)    # gid -> "host:port"
    # Atlas (dds_tpu/geo): the region THIS process runs in. Surfaces as
    # the `region` label on /health, /metrics, and Panopticon federation,
    # homes this process's proxy for read-local leases, and keys the
    # [retry] per-region overrides. Empty = geo-unaware.
    region: str = ""
    # REST "host:port" peers serving GET /shards (group status listeners
    # and/or other proxies) — bootstrap + gossip sources
    bootstrap: list[str] = field(default_factory=list)
    # long-poll hold requested from gossip peers (seconds); the serving
    # side caps it at proxy shards_wait_cap
    gossip_wait: float = 25.0
    # group-role status listener (GET /shards + /health + /metrics);
    # empty host = transport.host, port 0 = OS-assigned
    status_host: str = ""
    status_port: int = 0
    # enable POST /_reshard on proxy-role processes (operator control)
    admin_routes: bool = False
    # per-peer bootstrap attempt timeout; agent-RPC ack timeout
    bootstrap_timeout: float = 3.0
    rpc_timeout: float = 5.0
    # total Deadline budget one agent control RPC may spend across
    # retried attempts (rpc_timeout bounds each attempt); 0 derives
    # ~3.5x rpc_timeout
    rpc_budget: float = 0.0


@dataclass
class HelmsmanConfig:
    """Helmsman fleet autoscaler (dds_tpu/fleet/helmsman): closes the
    loop from SLO burn to fleet shape — splits a hot group onto a warm
    standby under distress, merges a cold group back when calm, promotes
    a standby over a dead group process. Hysteresis (streaks + cooldown)
    and a migrated-bytes budget keep it from thrashing; `pin` (or the
    controller's runtime `pin()`) freezes the shape for maintenance.
    DEPLOY.md "Self-driving capacity (Helmsman)" is the runbook."""

    enabled: bool = False
    # decision tick period (seconds)
    interval: float = 5.0
    # consecutive hot/cold ticks required before acting
    hot_streak: int = 3
    cold_streak: int = 6
    # a group's share of routed ops that counts as hot / cold
    hot_share: float = 0.5
    cold_share: float = 0.1
    # minimum routed ops per tick for shares to be trusted at all
    min_ops: int = 20
    # fleet shape bounds
    min_groups: int = 1
    max_groups: int = 8
    # quiet period after any action (seconds)
    cooldown: float = 30.0
    # migrated-bytes budget: at most `budget_bytes` of ciphertext may be
    # re-moved per sliding `budget_window` seconds (the BTS cost model —
    # goodput tracks how little you migrate)
    budget_bytes: int = 67108864
    budget_window: float = 600.0
    # a group whose Panopticon heartbeat is older than this is DEAD and
    # its keyspace is promoted onto a standby
    heartbeat_timeout: float = 15.0
    # start pinned (autoscaling frozen, liveness promotion still active)
    pin: bool = False


@dataclass
class GeoConfig:
    """Atlas geo-distribution plane (dds_tpu/geo): region-aware replica
    placement, TTL-leased read-local quorum geometry, and cross-region
    anti-entropy pairing. With `enabled = true` the constellation builder
    spreads each group's replicas across `regions` (placement = "span")
    or packs groups into round-robin home regions ("home"), carries the
    signed region assignment on the ShardMap, and — when `lease_ttl > 0`
    — installs per-group read-lease tables so an in-region replica can
    answer reads in one hop while every quorum its group closes includes
    the lease holders (the safety argument in dds_tpu/geo).
    DEPLOY.md "Geo-distribution (Atlas)" is the runbook."""

    enabled: bool = False
    regions: list[str] = field(default_factory=list)
    placement: str = "span"            # span | home
    # read-local leases: TTL per grant, renew when remaining < margin,
    # and the single-hop LocalRead budget before quorum fallback.
    # lease_ttl = 0 disables leases (placement/labels still apply).
    lease_ttl: float = 2.0
    lease_renew_margin: float = 0.5
    local_read_timeout: float = 0.75
    # anti-entropy cross-region pairing: probability a pull round goes
    # cross-region, plus extra de-synchronising sleep before WAN rounds
    cross_region_bias: float = 0.5
    cross_jitter: float = 0.5


@dataclass
class RetryConfig:
    """Per-region retry/deadline overrides (`[retry]`, Atlas): a proxy in
    a 100-300 ms-RTT region needs different budgets than a same-rack one.
    `profiles` maps a region name to an override table applied over the
    [proxy] defaults for processes whose `[fabric] region` matches:

        [retry.profiles.eu]
        rtt-ms = 120                 # derivation input, see below
        request-budget = 4.0         # explicit keys win over derivation

    With `rtt-ms` set, unset keys derive from one WAN round trip R (the
    floor any cross-region attempt must clear; DEPLOY.md "Geo-
    distribution (Atlas)" documents the rationale): retry-backoff = 2R
    (first backoff outlives one in-flight straggler), retry-max-delay =
    8R, request-budget = 24R (~3 attempts at max backoff), and
    retry-after-hint = 2R."""

    profiles: dict = field(default_factory=dict)

    _KEYS = ("request_budget", "retry_backoff", "retry_max_delay",
             "retry_after_hint", "intranet_request_timeout")

    def overrides_for(self, region: str) -> dict:
        """Effective [proxy]-field overrides for `region` (snake_case
        keys); {} when the region has no profile."""
        prof = {k.replace("-", "_"): v
                for k, v in dict(self.profiles.get(region, {})).items()}
        out: dict = {}
        rtt_ms = prof.pop("rtt_ms", None)
        if rtt_ms is not None:
            rtt = float(rtt_ms) / 1e3
            out["retry_backoff"] = 2.0 * rtt
            out["retry_max_delay"] = 8.0 * rtt
            out["request_budget"] = 24.0 * rtt
            out["retry_after_hint"] = 2.0 * rtt
        unknown = set(prof) - set(self._KEYS)
        if unknown:
            raise ValueError(
                f"unknown [retry.profiles.{region}] keys {sorted(unknown)}"
            )
        for k, v in prof.items():
            out[k] = float(v)
        return out


@dataclass
class ChaosNetConfig:
    """Seeded WAN fault fabric (`[chaos]`, Atlas): named link profiles
    applied to the ChaosNet that `attacks.chaos_enabled` wraps the
    transport in. `profiles` maps a directed ("eu->us") or symmetric
    ("eu<->us") region pair to a WAN preset name ("wan-100" | "wan-200" |
    "wan-300", RTT milliseconds) or an explicit spec table (delay-ms /
    jitter-ms / drop / duplicate / reorder / corrupt) — parsed by
    dds_tpu/geo/wan.py, the ONE loader tests and benchmarks share so both
    see the identical seeded WAN. `scale` shrinks every delay uniformly
    (tests run the same topology at a fraction of real time)."""

    profiles: dict = field(default_factory=dict)
    scale: float = 1.0


@dataclass
class AttackConfig:
    enabled: bool = False
    # crash | byzantine | partition | delay | flood | heal (the network
    # attacks need chaos_enabled so a ChaosNet fabric exists to drive).
    # "stale_tag" arms a Meridian group process's replicas as
    # properly-MAC'd stale-read forgers (malicious/trudy.StaleTagForger)
    # — the cross-host audit regression schedule.
    type: str = "byzantine"
    # wrap the transport in a seeded ChaosNet (core/chaos.py) and use the
    # Nemesis driver, so deployments can soak under deterministic network
    # fault schedules; the seed reproduces the exact fault trace
    chaos_enabled: bool = False
    chaos_seed: int = 0


@dataclass
class HeliographConfig:
    """Heliograph active canary plane (`[heliograph]`, dds_tpu/obs/
    heliograph): a supervised async prober per proxy (and per Meridian
    process) owning the reserved `__heliograph__` tenant, continuously
    driving golden transactions through the real client crypto path —
    PutSet -> quorum write -> GetSet read-your-write, SumAll/MultAll
    decrypt-and-compare over a known plaintext population, one Spyglass
    search, one Prism MatVec — and verifying every answer by decrypting
    it. Outcomes (ok / slow / wrong-answer / unreachable) land in the
    CanaryLedger (`GET /canary`, `/metrics`, fleet-federated as
    `GET /fleet/canary`), synthetic per-route-class SLO streams, a
    Watchtower incident on wrong-answer, and Helmsman's region-down
    signal on sustained unreachable. DEPLOY.md "Active probing
    (Heliograph)" is the runbook."""

    enabled: bool = False
    # seconds between probe cycles (each cycle runs every probe kind once)
    cadence: float = 5.0
    # fraction of cadence randomized per sleep (0.5 = +/-50%): jittered
    # scheduling so a fleet of probers never phase-locks into a thundering
    # herd against one proxy
    jitter: float = 0.5
    # per-probe wall deadline (seconds); a probe past it is `unreachable`
    deadline: float = 2.0
    # latency above which an otherwise-correct probe is verdicted `slow`
    slow_ms: float = 250.0
    # known plaintext rows the canary keyspace holds (aggregate ground truth)
    population: int = 4
    # canary crypto domain key sizes — deliberately small: the prober
    # measures the PIPE, not the modmul kernel, and generates at startup
    paillier_bits: int = 512
    rsa_bits: int = 512
    # explicit rate bound on the canary admission carve-out: probe
    # requests bypass tenant-fair admission but pass a dedicated token
    # bucket, so a wedged/looping prober can never self-DoS the fleet
    rate: float = 20.0
    burst: float = 40.0
    # probe kinds to run (subset of: putget sum mult search matvec)
    probes: list[str] = field(
        default_factory=lambda: ["putget", "sum", "mult", "search", "matvec"])
    # extra proxy targets ("host:port" or "region=host:port") probed
    # round-robin in addition to the local loopback edge — per-region /
    # per-group targeting in fleets; [] probes only the local process
    targets: list[str] = field(default_factory=list)
    # consecutive unreachable probe cycles against one region before the
    # ledger flags it to Helmsman's region_down/promotion signal
    unreachable_streak: int = 3


@dataclass
class DDSConfig:
    replicas: ReplicaTopology = field(default_factory=ReplicaTopology)
    security: SecurityConfig = field(default_factory=SecurityConfig)
    recovery: RecoveryConfig = field(default_factory=RecoveryConfig)
    proxy: ProxySettings = field(default_factory=ProxySettings)
    transport: TransportConfig = field(default_factory=TransportConfig)
    client: ClientSettings = field(default_factory=ClientSettings)
    attacks: AttackConfig = field(default_factory=AttackConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)
    shard: ShardConfig = field(default_factory=ShardConfig)
    analytics: AnalyticsConfig = field(default_factory=AnalyticsConfig)
    admission: AdmissionConfig = field(default_factory=AdmissionConfig)
    resident: ResidentConfig = field(default_factory=ResidentConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    search: SearchConfig = field(default_factory=SearchConfig)
    fabric: FabricConfig = field(default_factory=FabricConfig)
    helmsman: HelmsmanConfig = field(default_factory=HelmsmanConfig)
    tenancy: TenancyConfig = field(default_factory=TenancyConfig)
    crypto: CryptoConfig = field(default_factory=CryptoConfig)
    geo: GeoConfig = field(default_factory=GeoConfig)
    retry: RetryConfig = field(default_factory=RetryConfig)
    chaos: ChaosNetConfig = field(default_factory=ChaosNetConfig)
    heliograph: HeliographConfig = field(default_factory=HeliographConfig)
    debug: bool = False

    # ------------------------------------------------------------- loading

    @staticmethod
    def _build(cls, data):
        if dataclasses.is_dataclass(cls) and isinstance(data, dict):
            fields = {f.name: f for f in dataclasses.fields(cls)}
            kwargs = {}
            for k, v in data.items():
                k = k.replace("-", "_")
                if k not in fields:
                    raise ValueError(f"unknown config key {k!r} for {cls.__name__}")
                ftype = fields[k].type
                sub = _SUBSECTIONS.get((cls.__name__, k))
                kwargs[k] = DDSConfig._build(sub, v) if sub else v
            return cls(**kwargs)
        return data

    @staticmethod
    def from_dict(data: dict) -> "DDSConfig":
        return DDSConfig._build(DDSConfig, data)

    @staticmethod
    def load(path: str | pathlib.Path) -> "DDSConfig":
        p = pathlib.Path(path)
        if p.suffix == ".toml":
            try:
                import tomllib
            except ModuleNotFoundError:  # py<3.11: tomli is API-identical
                import tomli as tomllib

            data = tomllib.loads(p.read_text())
        else:
            data = json.loads(p.read_text())
        return DDSConfig.from_dict(data)


_SUBSECTIONS = {
    ("DDSConfig", "replicas"): ReplicaTopology,
    ("DDSConfig", "security"): SecurityConfig,
    ("DDSConfig", "recovery"): RecoveryConfig,
    ("DDSConfig", "proxy"): ProxySettings,
    ("DDSConfig", "transport"): TransportConfig,
    ("DDSConfig", "client"): ClientSettings,
    ("DDSConfig", "attacks"): AttackConfig,
    ("DDSConfig", "obs"): ObsConfig,
    ("DDSConfig", "shard"): ShardConfig,
    ("DDSConfig", "analytics"): AnalyticsConfig,
    ("DDSConfig", "admission"): AdmissionConfig,
    ("DDSConfig", "resident"): ResidentConfig,
    ("DDSConfig", "storage"): StorageConfig,
    ("DDSConfig", "search"): SearchConfig,
    ("DDSConfig", "fabric"): FabricConfig,
    ("DDSConfig", "helmsman"): HelmsmanConfig,
    ("DDSConfig", "tenancy"): TenancyConfig,
    ("DDSConfig", "crypto"): CryptoConfig,
    ("DDSConfig", "geo"): GeoConfig,
    ("DDSConfig", "retry"): RetryConfig,
    ("DDSConfig", "chaos"): ChaosNetConfig,
    ("DDSConfig", "heliograph"): HeliographConfig,
    ("ClientSettings", "data_table"): DataTableConfig,
    ("ObsConfig", "fleet"): FleetObsConfig,
}
