"""Per-node suspicion strike counter with random load balancing.

Counterpart of `utils/TrustedNodesList.scala`: 3 strikes exclude a node
from the trusted set; `defer_to` picks a random trusted node.
"""

from __future__ import annotations

import random

STRIKE_LIMIT = 3


class NoTrustedNodesError(RuntimeError):
    """Every member is excluded (3-strike) — nothing left to coordinate a
    quorum. Typed (but still a RuntimeError for old callers) so the REST
    layer can degrade to 503 + Retry-After instead of a 500."""


class TrustedNodesList:
    def __init__(self, nodes: list[str] | None = None, rng: random.Random | None = None):
        self._strikes: dict[str, int] = {n: 0 for n in (nodes or [])}
        self._rng = rng or random.Random()

    def increment_suspicion(self, node: str) -> None:
        """Strike a MEMBER. Unknown names are ignored: striking would
        insert them into the membership with < limit strikes, so any
        unauthenticated message with a crafted sender could inject itself
        into the trusted set (and get picked as a coordinator)."""
        if node in self._strikes:
            self._strikes[node] += 1

    def suspicions(self) -> dict[str, int]:
        """Current strike count per member (observability snapshot)."""
        return dict(self._strikes)

    def get_untrusted(self) -> list[str]:
        return [n for n, s in self._strikes.items() if s >= STRIKE_LIMIT]

    def get_trusted(self) -> list[str]:
        return [n for n, s in self._strikes.items() if s < STRIKE_LIMIT]

    def get_all(self) -> list[str]:
        return list(self._strikes)

    def reset(self, nodes: list[str]) -> None:
        """Replace the membership, keeping strikes of surviving nodes."""
        self._strikes = {n: self._strikes.get(n, 0) for n in nodes}

    def merge(self, nodes: list[str]) -> None:
        """Add members without dropping existing ones (strikes kept). Used
        when a partial view arrives — e.g. the supervisor's freshest-half
        `ActiveReplicas` — that must not shrink quorum membership."""
        for n in nodes:
            self._strikes.setdefault(n, 0)

    def defer_to(self, exclude=(), prefer=()) -> str:
        """Pick a random trusted node, avoiding `exclude` when any other
        trusted node remains (used to pick a genuinely different
        coordinator for corroborating re-reads). `prefer` narrows the
        choice to those nodes when any of them qualify (the reference
        proxy load-balances over the supervisor's freshest-half list,
        `DDSRestServer.scala:139-147`)."""
        trusted = self.get_trusted()
        if not trusted:
            raise NoTrustedNodesError("no trusted nodes left")
        candidates = [n for n in trusted if n not in exclude]
        preferred = [n for n in candidates if n in prefer]
        return self._rng.choice(preferred or candidates or trusted)
