"""Structured tracing: per-phase timing events + counters.

The reference's only observability is three debug flags gating `println`s
and a client ops/s printout (SURVEY.md §5.1, `dds-system.conf:61-62`,
`clt/DDSHttpClient.scala:410-415`). This module is the structured upgrade
called for there: every subsystem records named spans (HTTP route time,
ABD quorum RTT, crypto kernel time) into a bounded in-memory ring that can
be summarized (count/total/mean/p95) or dumped as JSONL for offline
analysis. Overhead is one perf_counter pair and a deque append per span.

Usage:

    from dds_tpu.utils.trace import tracer
    with tracer.span("abd.fetch", key=key):
        ...
    tracer.count("abd.suspect")
    print(tracer.summary())
"""

from __future__ import annotations

import collections
import contextlib
import json
import threading
import time
from dataclasses import dataclass, field


@dataclass
class SpanRecord:
    ts: float
    name: str
    dur_ms: float
    meta: dict


@dataclass
class Tracer:
    """Thread-safe bounded event recorder."""

    max_events: int = 65536
    enabled: bool = True
    _events: collections.deque = field(init=False, repr=False)
    _counters: collections.Counter = field(init=False, repr=False)
    _lock: threading.Lock = field(init=False, repr=False)

    def __post_init__(self):
        self._events = collections.deque(maxlen=self.max_events)
        self._counters = collections.Counter()
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def span(self, name: str, **meta):
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, (time.perf_counter() - t0) * 1e3, **meta)

    def record(self, name: str, dur_ms: float, **meta) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._events.append(SpanRecord(time.time(), name, dur_ms, meta))

    def count(self, name: str, n: int = 1) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._counters[name] += n

    # ------------------------------------------------------------- reporting

    def events(self, name: str | None = None) -> list[SpanRecord]:
        with self._lock:
            evs = list(self._events)
        return [e for e in evs if name is None or e.name == name]

    def counters(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def summary(self) -> dict[str, dict]:
        """Per-span-name {count, total_ms, mean_ms, p50_ms, p95_ms}."""
        groups: dict[str, list[float]] = collections.defaultdict(list)
        for e in self.events():
            groups[e.name].append(e.dur_ms)
        out = {}
        for name, durs in sorted(groups.items()):
            durs.sort()
            k = len(durs)
            out[name] = {
                "count": k,
                "total_ms": round(sum(durs), 3),
                "mean_ms": round(sum(durs) / k, 3),
                "p50_ms": round(durs[k // 2], 3),
                "p95_ms": round(durs[min(k - 1, int(k * 0.95))], 3),
            }
        for name, n in self.counters().items():
            out.setdefault(name, {})["count"] = (
                out.get(name, {}).get("count", 0) + n
            )
        return out

    def dump_jsonl(self, path: str) -> int:
        evs = self.events()
        with open(path, "w") as f:
            for e in evs:
                f.write(
                    json.dumps(
                        {"ts": e.ts, "name": e.name, "dur_ms": e.dur_ms, **e.meta}
                    )
                    + "\n"
                )
        return len(evs)

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._counters.clear()


# process-wide default tracer (subsystems import this)
tracer = Tracer()
