"""Structured tracing: causally-linked spans + point events + counters.

The reference's only observability is three debug flags gating `println`s
and a client ops/s printout (SURVEY.md §5.1, `dds-system.conf:61-62`,
`clt/DDSHttpClient.scala:410-415`). This module is the structured upgrade
called for there, extended by Telescope (dds_tpu/obs) into DISTRIBUTED
tracing: every recorded span carries `(trace_id, span_id, parent_id)` from
the contextvar-propagated `obs.context`, so one REST request yields a span
tree — HTTP route -> quorum round -> per-replica handler -> crypto kernel —
instead of an anonymous flat ring. Point `event`s (chaos injections, retry
attempts, breaker transitions, attacks) annotate the same tree with zero
duration. Overhead is one perf_counter pair and a deque append per span.

Usage:

    from dds_tpu.utils.trace import tracer
    with tracer.span("abd.fetch", key=key) as meta:
        meta["coordinator"] = coord      # annotate mid-span
    tracer.event("breaker.open", target=coord)
    tracer.count("abd.suspect")
    print(tracer.summary())              # span stats only
    print(tracer.counters())             # counters, separately
"""

from __future__ import annotations

import collections
import contextlib
import json
import logging
import math
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from dds_tpu.obs import context as obs_context


@dataclass
class SpanRecord:
    ts: float
    name: str
    dur_ms: float
    meta: dict
    trace_id: Optional[str] = None
    span_id: Optional[str] = None
    parent_id: Optional[str] = None
    kind: str = "span"  # "span" (timed) | "event" (zero-duration annotation)


def _percentile(sorted_durs: list[float], q: float) -> float:
    """Nearest-rank percentile over an ascending list (exact for small k:
    p95 of 20 samples is the 19th value, not the max)."""
    k = len(sorted_durs)
    return sorted_durs[max(0, min(k - 1, math.ceil(q * k) - 1))]


@dataclass
class Tracer:
    """Thread-safe bounded event recorder."""

    max_events: int = 65536
    enabled: bool = True
    _events: collections.deque = field(init=False, repr=False)
    _counters: collections.Counter = field(init=False, repr=False)
    _lock: threading.Lock = field(init=False, repr=False)
    _subscribers: list = field(init=False, repr=False)
    _notifying: threading.local = field(init=False, repr=False)

    def __post_init__(self):
        self._events = collections.deque(maxlen=self.max_events)
        self._counters = collections.Counter()
        self._lock = threading.Lock()
        self._subscribers = []
        self._notifying = threading.local()

    # ---------------------------------------------------------- subscribers

    def subscribe(self, fn) -> None:
        """Register `fn(SpanRecord)` to be called (outside the ring lock)
        for every record. The consumer side of Watchtower: an online
        auditor sees each span/event as it lands instead of polling the
        ring. Subscribers must be cheap and must not raise — exceptions
        are swallowed so telemetry consumers can never break the paths
        being observed."""
        if fn not in self._subscribers:
            self._subscribers.append(fn)

    def unsubscribe(self, fn) -> None:
        if fn in self._subscribers:
            self._subscribers.remove(fn)

    def _notify(self, rec: "SpanRecord") -> None:
        # re-entrancy guard: a subscriber that records a span of its own
        # must not recurse into the subscriber chain again on this thread
        if getattr(self._notifying, "active", False):
            return
        self._notifying.active = True
        try:
            for fn in list(self._subscribers):
                try:
                    fn(rec)
                except Exception:  # noqa: BLE001 — observers never break observed paths
                    logging.getLogger("dds.trace").exception(
                        "trace subscriber failed"
                    )
        finally:
            self._notifying.active = False

    @contextlib.contextmanager
    def span(self, name: str, /, _ctx: Optional[obs_context.SpanContext] = None,
             **meta):
        """Timed span. Yields the (mutable) meta dict so callers can
        annotate facts learned mid-span (the chosen coordinator, a batch
        size). Installs a child trace context for the duration, so spans
        recorded inside — including ones in tasks spawned inside (asyncio
        copies contextvars at task creation) — become children."""
        if not self.enabled:
            yield meta
            return
        ctx = _ctx if _ctx is not None else obs_context.child()
        token = obs_context.attach(ctx)
        t0 = time.perf_counter()
        try:
            yield meta
        finally:
            obs_context.detach(token)
            self.record(name, (time.perf_counter() - t0) * 1e3, _ctx=ctx, **meta)

    def record(self, name: str, dur_ms: float, /,
               _ctx: Optional[obs_context.SpanContext] = None,
               _kind: str = "span", **meta) -> None:
        if not self.enabled:
            return
        ctx = _ctx if _ctx is not None else obs_context.current()
        tid, sid, pid = (
            (ctx.trace_id, ctx.span_id, ctx.parent_id) if ctx is not None
            else (None, None, None)
        )
        rec = SpanRecord(time.time(), name, dur_ms, meta, tid, sid, pid, _kind)
        with self._lock:
            self._events.append(rec)
        if self._subscribers:
            self._notify(rec)

    def event(self, name: str, /, **meta) -> None:
        """Zero-duration annotation attached to the ACTIVE trace (chaos
        injections, retry attempts, breaker transitions, attacks). Outside
        any trace the event is recorded unlinked rather than minting a
        one-event orphan trace."""
        if not self.enabled:
            return
        cur = obs_context.current()
        ctx = obs_context.child(cur) if cur is not None else None
        self.record(name, 0.0, _ctx=ctx, _kind="event", **meta)

    def count(self, name: str, n: int = 1) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._counters[name] += n

    # ------------------------------------------------------------- reporting

    def events(self, name: str | None = None) -> list[SpanRecord]:
        with self._lock:
            evs = list(self._events)
        return [e for e in evs if name is None or e.name == name]

    def trace_events(self, trace_id: str) -> list[SpanRecord]:
        """All recorded spans/events of one trace, in record order."""
        with self._lock:
            evs = list(self._events)
        return [e for e in evs if e.trace_id == trace_id]

    def counters(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def summary(self) -> dict[str, dict]:
        """Per-span-name {count, total_ms, mean_ms, p50_ms, p95_ms} over
        TIMED spans only. Counters are a different quantity (occurrences,
        not durations) and zero-duration events would deflate the means —
        both are reported separately (`counters()`, the /_trace route)."""
        groups: dict[str, list[float]] = collections.defaultdict(list)
        for e in self.events():
            if e.kind == "span":
                groups[e.name].append(e.dur_ms)
        out = {}
        for name, durs in sorted(groups.items()):
            durs.sort()
            k = len(durs)
            out[name] = {
                "count": k,
                "total_ms": round(sum(durs), 3),
                "mean_ms": round(sum(durs) / k, 3),
                "p50_ms": round(_percentile(durs, 0.50), 3),
                "p95_ms": round(_percentile(durs, 0.95), 3),
            }
        return out

    @staticmethod
    def event_dict(e: SpanRecord) -> dict:
        """One JSON-safe record. Meta lives under its own "meta" key so a
        span recorded with meta named `name`/`ts`/`dur_ms` can never
        shadow the record fields."""
        rec = {"ts": e.ts, "name": e.name, "dur_ms": e.dur_ms, "kind": e.kind}
        if e.trace_id is not None:
            rec["trace_id"] = e.trace_id
            rec["span_id"] = e.span_id
            rec["parent_id"] = e.parent_id
        if e.meta:
            rec["meta"] = e.meta
        return rec

    def dump_jsonl(self, path: str) -> int:
        evs = self.events()
        with open(path, "w") as f:
            for e in evs:
                f.write(json.dumps(self.event_dict(e), default=str) + "\n")
        return len(evs)

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._counters.clear()


def _default_tracer() -> Tracer:
    """Process-wide tracer, env-tunable: DDS_OBS_RING sizes the span ring
    (default 65536), DDS_OBS_TRACE=0 disables recording entirely."""
    try:
        ring = int(os.environ.get("DDS_OBS_RING", "65536"))
    except ValueError:
        ring = 65536
    enabled = os.environ.get("DDS_OBS_TRACE", "").strip().lower() not in (
        "0", "false", "off", "no",
    )
    return Tracer(max_events=max(16, ring), enabled=enabled)


# process-wide default tracer (subsystems import this)
tracer = _default_tracer()
