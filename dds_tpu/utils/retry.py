"""Async retry-with-fixed-backoff, counterpart of `utils/FutureRetry.scala`."""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, TypeVar

T = TypeVar("T")


async def retry(f: Callable[[], Awaitable[T]], delay: float, retries: int) -> T:
    """Run `f`; on exception wait `delay` seconds and retry up to `retries`
    more times; the final failure propagates."""
    for attempt in range(retries + 1):
        try:
            return await f()
        except Exception:
            if attempt == retries:
                raise
            await asyncio.sleep(delay)
    raise AssertionError("unreachable")
