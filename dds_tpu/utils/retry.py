"""Deadline-propagated retry: exponential backoff + full jitter + breakers.

Replaces the fixed-backoff `retry(f, delay, retries)` loop (counterpart of
`utils/FutureRetry.scala`) with the coherent budget story the BFT stack
needs under adversarial schedules:

- `Deadline`: an absolute time budget minted once at the edge (the REST
  layer) and passed DOWN the call stack, so every nested retry loop and
  per-attempt timeout shrinks to what is left of the caller's budget
  instead of compounding its own fixed 5 s timeout per layer.
- `retry_deadline`: retry with exponential backoff and *full jitter*
  (delay ~ U(0, min(cap, base*mult^attempt)) — the AWS-style variant that
  decorrelates retry storms after a partition heals). When the budget
  cannot fit another attempt it raises `DeadlineExceededError`, a typed
  signal the REST layer maps to 503 + Retry-After instead of hanging.
- `CircuitBreaker`: per-target closed/open/half-open state. Transient
  unreachability (timeouts) belongs here — it self-heals via the
  half-open probe once the target returns — while cryptographic protocol
  violations stay on the PERMANENT 3-strike suspicion counter
  (`utils/trust.TrustedNodesList`). Splitting the two is what lets a
  fully-partitioned cluster serve again after heal without a restart.

Everything takes injectable `clock` / `sleep` / `rng` so the unit tests
(tests/test_retry.py) run on a fake clock instead of wall time.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass
from typing import Awaitable, Callable, Optional, TypeVar

from dds_tpu.obs.metrics import metrics
from dds_tpu.utils.trace import tracer

T = TypeVar("T")


class DeadlineExceededError(Exception):
    """The operation's time budget ran out before an attempt succeeded.

    Carries enough context for the caller's degradation decision: how many
    attempts ran, how long they took, and the last underlying failure."""

    def __init__(
        self,
        message: str,
        attempts: int = 0,
        elapsed: float = 0.0,
        last_error: Optional[BaseException] = None,
    ):
        super().__init__(message)
        self.attempts = attempts
        self.elapsed = elapsed
        self.last_error = last_error


class Deadline:
    """An absolute time budget, created once and passed down the stack."""

    def __init__(self, budget: float, clock: Callable[[], float] = time.monotonic):
        self.budget = budget
        self._clock = clock
        self.start = clock()
        self.at = self.start + budget

    def remaining(self) -> float:
        return self.at - self._clock()

    def elapsed(self) -> float:
        return self._clock() - self.start

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0

    def timeout(self, per_attempt: float) -> float:
        """Per-attempt timeout clipped to what is left of the budget."""
        return max(0.0, min(per_attempt, self.remaining()))

    def __repr__(self) -> str:  # visible in DeadlineExceededError messages
        return f"Deadline({self.budget:.3f}s, {self.remaining():.3f}s left)"


@dataclass
class RetryPolicy:
    """Exponential backoff + full jitter. `max_attempts=None` means the
    deadline alone governs (the chaos-tolerant default): attempts continue
    as long as the budget can fit another backoff + try."""

    base: float = 0.05
    multiplier: float = 2.0
    max_delay: float = 2.0
    max_attempts: Optional[int] = None
    jitter: bool = True

    def backoff(self, attempt: int, rng: random.Random) -> float:
        """Delay before attempt `attempt`+1 (attempt counts from 0)."""
        cap = min(self.max_delay, self.base * (self.multiplier ** attempt))
        return rng.uniform(0.0, cap) if self.jitter else cap


async def retry_deadline(
    f: Callable[[], Awaitable[T]],
    deadline: Deadline,
    policy: Optional[RetryPolicy] = None,
    retry_on: tuple = (Exception,),
    rng: Optional[random.Random] = None,
    sleep: Callable[[float], Awaitable[None]] = asyncio.sleep,
) -> T:
    """Run `f` until it succeeds, the policy's attempts run out (the last
    real error propagates), or the deadline cannot fit another backoff
    (typed `DeadlineExceededError`). Exceptions outside `retry_on`
    propagate immediately — a programming error is not a network blip."""
    policy = policy or RetryPolicy()
    rng = rng or random
    attempt = 0
    while True:
        if deadline.expired:
            raise DeadlineExceededError(
                f"budget exhausted before attempt {attempt + 1} ({deadline!r})",
                attempts=attempt,
                elapsed=deadline.elapsed(),
            )
        try:
            return await f()
        except retry_on as e:
            attempt += 1
            # annotate the active trace + the retry-pressure counter: under
            # a chaos schedule these are how a post-mortem distinguishes
            # "slow but clean" from "every round fought the network"
            tracer.event("retry.attempt", attempt=attempt,
                         error=type(e).__name__)
            metrics.inc("dds_retry_attempts_total", error=type(e).__name__,
                        help="storage-layer attempts that failed and retried")
            if policy.max_attempts is not None and attempt >= policy.max_attempts:
                raise
            delay = policy.backoff(attempt - 1, rng)
            if delay >= deadline.remaining():
                # sleeping past the deadline buys nothing: degrade NOW with
                # the typed error instead of hanging out the budget
                raise DeadlineExceededError(
                    f"{deadline.budget:.3f}s budget exhausted after "
                    f"{attempt} attempt(s): {e!r}",
                    attempts=attempt,
                    elapsed=deadline.elapsed(),
                    last_error=e,
                ) from e
            await sleep(delay)


async def retry(f: Callable[[], Awaitable[T]], delay: float, retries: int) -> T:
    """Legacy fixed-backoff loop (`utils/FutureRetry.scala` parity), kept
    for harness code that wants N dumb attempts with a constant pause.
    Production paths use `retry_deadline`."""
    for attempt in range(retries + 1):
        try:
            return await f()
        except Exception:
            if attempt == retries:
                raise
            await asyncio.sleep(delay)
    raise AssertionError("unreachable")


class CircuitBreaker:
    """closed -> (failure_threshold consecutive failures) -> open ->
    (reset_timeout elapses) -> half-open -> one success closes / one
    failure re-opens.

    Guards a single target (one coordinator). Transient-failure state only:
    it self-heals, unlike the permanent `TrustedNodesList` strikes reserved
    for cryptographic protocol violations. Half-open deliberately admits
    concurrent probes (no single-probe token): the first recorded outcome
    resolves the state, and a duplicate probe against a healed target is
    harmless while a probe token leaked to a never-chosen candidate would
    wedge the breaker."""

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(
        self,
        failure_threshold: int = 3,
        reset_timeout: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
        name: str = "",
    ):
        self.failure_threshold = failure_threshold
        self.reset_timeout = reset_timeout
        self.name = name  # guarded target, for telemetry attribution
        self._clock = clock
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0

    @property
    def state(self) -> str:
        self._maybe_half_open()
        return self._state

    def _transition(self, state: str) -> None:
        if state == self._state:
            return
        self._state = state
        tracer.event("breaker." + state, target=self.name)
        metrics.inc("dds_breaker_transitions_total", state=state,
                    target=self.name,
                    help="circuit-breaker state transitions per target")
        if state == self.OPEN:
            # a breaker opening IS a fault: freeze the telemetry that led
            # here (no-op unless a flight directory is configured)
            from dds_tpu.obs.flight import flight

            flight.record("breaker_open", target=self.name)

    def _maybe_half_open(self) -> None:
        if (
            self._state == self.OPEN
            and self._clock() - self._opened_at >= self.reset_timeout
        ):
            self._transition(self.HALF_OPEN)

    def allow(self) -> bool:
        """May the caller route a request at this target right now?"""
        self._maybe_half_open()
        return self._state != self.OPEN

    def half_open_eta(self) -> float:
        """Seconds until this breaker's next half-open probe (0 when it is
        not refusing traffic). The honest Retry-After for a degraded
        response: clients coming back any sooner are guaranteed to find
        the same open breaker."""
        self._maybe_half_open()
        if self._state != self.OPEN:
            return 0.0
        return max(0.0, self.reset_timeout - (self._clock() - self._opened_at))

    def record_success(self) -> None:
        self._transition(self.CLOSED)
        self._failures = 0

    def record_failure(self) -> None:
        self._maybe_half_open()
        if self._state == self.HALF_OPEN:
            self._trip()  # failed probe: back to open, timer restarted
            return
        self._failures += 1
        if self._failures >= self.failure_threshold:
            self._trip()

    def _trip(self) -> None:
        self._transition(self.OPEN)
        self._failures = 0
        self._opened_at = self._clock()
