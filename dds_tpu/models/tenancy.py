"""Bastion tenant crypto domains: per-tenant key families with a lifecycle.

The paper's DDS model assumes ONE client keyring for the whole store;
production multi-tenancy needs one *crypto domain per tenant* so that a
key compromise, a rotation, or a deletion request is scoped to a single
tenant. `TenantKeyring` owns a versioned family of `HEKeys` per tenant
(Paillier/DET/OPE/LSE/RSA/HMAC — the full six-scheme set, plus a derived
per-tenant HMAC secret for transport signing) and three lifecycle verbs:

- **keys_for(tenant)** — lazy generation on first touch. Every tenant
  gets its OWN Paillier modulus, so mixed-tenant folds can never share a
  ciphertext domain by accident; the fold planes group operands by
  modulus (``_fold_pending`` is modulus-keyed), which means same-tenant
  traffic still coalesces into the fused Lodestone dispatch while
  cross-tenant operands land in separate groups by construction.
- **rotate(tenant)** — mint a new epoch; the previous epoch enters a
  *grace window* during which its ciphertexts still decrypt
  (`decrypt_any` walks active-then-grace epochs and reports which epoch
  matched, so callers can re-encrypt-on-read and converge the store onto
  the new keys without a stop-the-world rewrite).
- **shred(tenant)** — crypto-shredding as deletion: every epoch's
  Paillier key is scrubbed (`PaillierKey.scrub()` closes its Sanctum
  plans and zero-fills the derived copies), symmetric key bytes are
  dropped, and the tenant enters a terminal state where every further
  key access raises the typed `TenantShredded`. Dropping the keys IS the
  deletion — ciphertexts at rest become permanently undecryptable.

Every lifecycle transition is flight-recorded (kind ``tenant_rotate`` /
``tenant_shred``) and counted in the metrics registry, so an auditor can
reconstruct who lost the ability to decrypt what, and when.

Thread-safety: one lock guards the tenant table; key *generation* runs
outside the lock (prime search can take milliseconds) with a per-tenant
pending marker so concurrent first touches generate once.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from dds_tpu.models.keys import HEKeys
from dds_tpu.obs.flight import flight
from dds_tpu.obs.metrics import metrics

__all__ = [
    "TenantKeyError",
    "TenantShredded",
    "KeyEpoch",
    "TenantKeyring",
]


class TenantKeyError(KeyError):
    """Typed refusal for tenant-keyspace violations (unknown tenant in
    strict mode, capacity exceeded, ...)."""


class TenantShredded(TenantKeyError):
    """Typed refusal raised for ANY key access after a tenant's crypto
    domain has been shredded. Deliberately terminal: shredding is
    deletion, so there is no recovery path short of re-onboarding the
    tenant under a fresh identity."""

    def __init__(self, tenant: str):
        super().__init__(f"tenant {tenant!r} crypto domain has been shredded")
        self.tenant = tenant


@dataclass
class KeyEpoch:
    """One generation of a tenant's key family."""

    version: int
    keys: HEKeys
    created_at: float
    # monotonic deadline after which a rotated-out epoch stops decrypting;
    # None while the epoch is active (no deadline)
    grace_until: float | None = None

    def state(self, now: float) -> str:
        if self.grace_until is None:
            return "active"
        return "grace" if now < self.grace_until else "expired"


@dataclass
class _TenantDomain:
    epochs: list[KeyEpoch] = field(default_factory=list)  # newest first
    shredded_at: float | None = None
    rotations: int = 0


class TenantKeyring:
    """Per-tenant versioned `HEKeys` families with rotate/shred lifecycle.

    ``paillier_bits``/``rsa_bits`` size generated families (tests and
    benchmarks pass small sizes; production uses the 2048/1024 defaults).
    ``grace`` is the rotation grace window in seconds. ``max_tenants``
    bounds the table — the same cardinality posture as the metrics
    registry: a keyring is per-tenant *state*, and unbounded state keyed
    by a wire-supplied label is a memory DoS.
    """

    def __init__(self, paillier_bits: int = 2048, rsa_bits: int = 1024,
                 grace: float = 300.0, max_tenants: int = 4096,
                 clock=time.monotonic):
        self.paillier_bits = int(paillier_bits)
        self.rsa_bits = int(rsa_bits)
        self.grace = float(grace)
        self.max_tenants = int(max_tenants)
        self._clock = clock
        self._lock = threading.Lock()
        self._domains: dict[str, _TenantDomain] = {}
        # tenants whose first generation is in flight (generation runs
        # outside the lock); waiters spin on the event
        self._pending: dict[str, threading.Event] = {}

    # ------------------------------------------------------------- internals

    def _generate(self, version: int) -> KeyEpoch:
        return KeyEpoch(
            version=version,
            keys=HEKeys.generate(self.paillier_bits, self.rsa_bits),
            created_at=self._clock(),
        )

    def _domain(self, tenant: str, create: bool = True) -> _TenantDomain:
        """Caller holds no lock; returns the domain, generating the first
        epoch if needed. Raises TenantShredded on shredded tenants."""
        while True:
            with self._lock:
                dom = self._domains.get(tenant)
                if dom is not None:
                    if dom.shredded_at is not None:
                        raise TenantShredded(tenant)
                    if dom.epochs:
                        return dom
                if not create:
                    raise TenantKeyError(f"unknown tenant {tenant!r}")
                ev = self._pending.get(tenant)
                if ev is None:
                    if len(self._domains) >= self.max_tenants:
                        raise TenantKeyError(
                            f"tenant keyring full ({self.max_tenants} "
                            f"tenants); refusing to onboard {tenant!r}"
                        )
                    ev = self._pending[tenant] = threading.Event()
                    self._domains.setdefault(tenant, _TenantDomain())
                    owner = True
                else:
                    owner = False
            if owner:
                try:
                    epoch = self._generate(1)
                    with self._lock:
                        dom = self._domains[tenant]
                        # a racing shred() wins: leave the domain shredded
                        if dom.shredded_at is None and not dom.epochs:
                            dom.epochs.append(epoch)
                finally:
                    with self._lock:
                        self._pending.pop(tenant, None)
                    ev.set()
            else:
                ev.wait()

    def _with_epoch_keys(self, tenant: str, epoch: KeyEpoch, fn):
        """Run `fn(keys)` against an epoch's key family, converting the
        symptoms of a shred racing the operation — keys unlinked, or the
        Paillier key zero-filled / its Sanctum plan closed mid-math —
        into the typed `TenantShredded` instead of letting garbage
        arithmetic errors escape to callers."""
        keys = epoch.keys
        try:
            if keys is None:
                raise TenantShredded(tenant)
            return fn(keys)
        except TenantShredded:
            raise
        except (ZeroDivisionError, AttributeError, RuntimeError):
            if self.is_shredded(tenant):
                raise TenantShredded(tenant) from None
            raise

    # ------------------------------------------------------------ public API

    def keys_for(self, tenant: str) -> HEKeys:
        """The tenant's ACTIVE key family, generated lazily on first
        touch. Raises `TenantShredded` after `shred(tenant)`."""
        return self._domain(tenant).epochs[0].keys

    def epochs_for(self, tenant: str) -> list[KeyEpoch]:
        """Decrypt candidates, newest first: the active epoch plus any
        rotated-out epochs still inside their grace window."""
        dom = self._domain(tenant)
        now = self._clock()
        with self._lock:
            # prune expired grace epochs while we're here
            dom.epochs = [e for e in dom.epochs if e.state(now) != "expired"]
            return list(dom.epochs)

    def version(self, tenant: str) -> int:
        return self._domain(tenant).epochs[0].version

    def known(self, tenant: str) -> bool:
        with self._lock:
            dom = self._domains.get(tenant)
            return dom is not None and dom.shredded_at is None

    def is_shredded(self, tenant: str) -> bool:
        with self._lock:
            dom = self._domains.get(tenant)
            return dom is not None and dom.shredded_at is not None

    def hmac_secret(self, tenant: str) -> bytes:
        """Per-tenant HMAC family: derived from the active epoch's LSE
        tag key and the tenant id, so it rotates with the family and dies
        with the shred."""
        import hashlib
        import hmac as _hmac

        epoch = self._domain(tenant).epochs[0]
        return self._with_epoch_keys(tenant, epoch, lambda keys: _hmac.new(
            keys.lse.k_tag,
            b"dds-tenant-hmac\x00" + tenant.encode() + b"\x00"
            + str(epoch.version).encode(),
            hashlib.sha256,
        ).digest())

    def rotate(self, tenant: str) -> int:
        """Mint a new epoch for `tenant`; the previous active epoch moves
        into the grace window (still decrypts until `grace` seconds pass,
        enabling re-encrypt-on-read convergence). Returns the new epoch
        version. Flight-recorded and counted."""
        self._domain(tenant)  # ensure exists / raise TenantShredded
        epoch = self._generate(0)  # version patched under the lock below
        with self._lock:
            dom = self._domains[tenant]
            if dom.shredded_at is not None:
                raise TenantShredded(tenant)
            now = self._clock()
            old = dom.epochs[0] if dom.epochs else None
            epoch.version = (old.version if old else 0) + 1
            if old is not None:
                old.grace_until = now + self.grace
            dom.epochs.insert(0, epoch)
            dom.rotations += 1
            version = epoch.version
        metrics.inc("dds_tenant_rotations_total", tenant=_cap(tenant),
                    help="tenant key-family rotations")
        flight.record("tenant_rotate", tenant=tenant, version=version,
                      grace=self.grace)
        return version

    def shred(self, tenant: str) -> dict:
        """Crypto-shred `tenant`: scrub every epoch's Paillier key
        (Sanctum plans closed + zero-filled, `_crt` dropped), unlink the
        symmetric families, and mark the tenant terminally shredded —
        every later key access raises `TenantShredded`. Returns an audit
        summary; flight-recorded. Idempotent."""
        with self._lock:
            dom = self._domains.setdefault(tenant, _TenantDomain())
            if dom.shredded_at is not None:
                return {"tenant": tenant, "already": True,
                        "epochs_scrubbed": 0}
            epochs, dom.epochs = dom.epochs, []
            dom.shredded_at = self._clock()
        for epoch in epochs:
            try:
                epoch.keys.psse.scrub()
            except Exception:  # pragma: no cover - scrub must not raise out
                pass
            # frozen dataclass: drop the field references so the symmetric
            # key bytes lose their last strong ref with the epoch object
            epoch.keys = None  # type: ignore[assignment]
        summary = {"tenant": tenant, "already": False,
                   "epochs_scrubbed": len(epochs)}
        metrics.inc("dds_tenant_shreds_total",
                    help="tenant crypto domains shredded (deletion events)")
        flight.record("tenant_shred", tenant=tenant,
                      epochs_scrubbed=len(epochs))
        return summary

    def encrypt(self, tenant: str, m: int) -> tuple[int, int]:
        """Encrypt under the ACTIVE epoch. Returns ``(ciphertext,
        epoch_version)`` — the version travels with the ciphertext (a
        Paillier ciphertext decrypted under the wrong modulus yields
        silent garbage, not an error, so decrypt MUST know its epoch)."""
        epoch = self._domain(tenant).epochs[0]
        ct = self._with_epoch_keys(
            tenant, epoch, lambda keys: keys.psse.public.encrypt(m))
        return ct, epoch.version

    def _epoch(self, tenant: str, version: int | None) -> KeyEpoch:
        epochs = self.epochs_for(tenant)
        if version is None:
            return epochs[0]
        for epoch in epochs:
            if epoch.version == version:
                return epoch
        raise TenantKeyError(
            f"tenant {tenant!r} epoch v{version} is not live (rotated out "
            f"past its grace window, or never existed)"
        )

    def decrypt(self, tenant: str, c: int, version: int | None = None) -> int:
        """CRT-decrypt `c` under epoch `version` (None = active). Grace
        epochs still decrypt until their window lapses — the
        re-encrypt-on-read runway. Raises `TenantShredded` after a shred
        (including one racing this call) and `TenantKeyError` when the
        epoch is no longer live."""
        epoch = self._epoch(tenant, version)
        return self._with_epoch_keys(
            tenant, epoch, lambda keys: keys.psse.decrypt(c))

    def reencrypt(self, tenant: str, c: int,
                  version: int | None = None) -> tuple[int, int, bool]:
        """Re-encrypt-on-read: decrypt `c` (minted under `version`) and,
        when that epoch is not the active one, return the plaintext
        freshly encrypted under the active keys. Returns ``(ciphertext,
        active_version, migrated)``; ``migrated=False`` hands back the
        input unchanged."""
        active = self._domain(tenant).epochs[0]
        if version is None or version == active.version:
            return c, active.version, False
        m = self.decrypt(tenant, c, version)
        ct, ver = self.encrypt(tenant, m)
        metrics.inc("dds_tenant_reencrypts_total",
                    help="rows migrated onto the active epoch by "
                         "re-encrypt-on-read during rotation grace")
        return ct, ver, True

    # --------------------------------------------------------------- surface

    def stats(self) -> dict:
        now = self._clock()
        with self._lock:
            tenants = {
                t: {
                    "shredded": dom.shredded_at is not None,
                    "rotations": dom.rotations,
                    "epochs": [
                        {"version": e.version, "state": e.state(now)}
                        for e in dom.epochs
                    ],
                }
                for t, dom in self._domains.items()
            }
        return {
            "tenants": len(tenants),
            "shredded": sum(1 for d in tenants.values() if d["shredded"]),
            "grace": self.grace,
            "domains": tenants,
        }

    def export_gauges(self, registry=metrics) -> None:
        with self._lock:
            total = len(self._domains)
            shredded = sum(
                1 for d in self._domains.values() if d.shredded_at is not None
            )
        registry.set("dds_tenant_domains", total,
                     help="tenant crypto domains onboarded")
        registry.set("dds_tenant_domains_shredded", shredded,
                     help="tenant crypto domains in the terminal "
                          "shredded state")


def _cap(tenant: str, limit: int = 40) -> str:
    # metric-label hygiene independent of the registry's overflow guard
    return tenant if len(tenant) <= limit else tenant[:limit]
