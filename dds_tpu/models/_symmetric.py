"""Shared symmetric-crypto primitives for the string schemes.

Single home for AES-256-CTR and base64 helpers used by det.py / rand.py /
searchable.py / keys.py — one implementation to audit and evolve.
"""

from __future__ import annotations

import base64

# `cryptography` is gated, not required at import: environments without it
# can still run the whole BFT/REST/chaos stack — only the AES-backed string
# schemes (det/rand/searchable) fail, loudly, at first USE.
try:
    from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

    _CRYPTO_ERR = None
except ModuleNotFoundError as _e:  # pragma: no cover - env-dependent
    Cipher = algorithms = modes = None
    _CRYPTO_ERR = _e


def aes_available() -> bool:
    """True when the `cryptography` package backs the AES schemes. Callers
    that can degrade (Heliograph's canary domain encrypts only synthetic
    plaintexts) check this instead of trapping the first-use error."""
    return Cipher is not None


def aes_ctr(key: bytes, iv: bytes, data: bytes) -> bytes:
    """AES-256-CTR keystream application (encrypt == decrypt)."""
    if Cipher is None:
        raise ModuleNotFoundError(
            "the AES-backed schemes (CHE/RND/searchable) need the "
            "'cryptography' package, which is not installed"
        ) from _CRYPTO_ERR
    c = Cipher(algorithms.AES(key), modes.CTR(iv)).encryptor()
    return c.update(data) + c.finalize()


def b64e(b: bytes) -> str:
    return base64.b64encode(b).decode()


def b64d(s: str) -> bytes:
    return base64.b64decode(s)


def b64e_url(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).decode().rstrip("=")


def b64d_url(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))
