"""Word-searchable encryption for strings (scheme tag "LSE").

Mirrors the role of `hlib.hj.mlib.HomoSearch` (`utils/SJHomoLibProvider.scala:
56,66`): the plaintext is recoverable by the key holder, and per-word
deterministic tags let an untrusted party test word membership without
decrypting.

Wire format (all base64, '.'-joined):  nonce.ciphertext.tag1.tag2...
where  ct = AES-256-CTR(k_enc, nonce, pt)  and  tag_i = HMAC(k_tag, word_i)[:12].

The nonce is SIV-style (a PRF of the plaintext), making encryption
deterministic: the proxy's `SearchEntry*` routes match records by ciphertext
equality (`DDSRestServer.scala:849-929` uses `HomoDet.compare`, i.e. string
equality), which requires equal plaintexts to encrypt equal.
"""

from __future__ import annotations

import hashlib
import hmac
from dataclasses import dataclass

from dds_tpu.models._symmetric import aes_ctr as _aes_ctr, b64d_url as _unb64, b64e_url as _b64


@dataclass(frozen=True)
class SearchKey:
    k_enc: bytes  # 32 bytes
    k_tag: bytes  # 32 bytes

    def _tag(self, word: str) -> str:
        return _b64(hmac.new(self.k_tag, word.encode(), hashlib.sha256).digest()[:12])

    def encrypt(self, pt: str) -> str:
        # SIV nonce keyed with k_enc, NOT k_tag: trapdoors/tags are public
        # HMAC(k_tag, word) values, so a k_tag-derived nonce would collide
        # with the tag of a 'siv|...' word and leak record equality
        nonce = hmac.new(self.k_enc, b"siv|" + pt.encode(), hashlib.sha256).digest()[:16]
        ct = _aes_ctr(self.k_enc, nonce, pt.encode())
        tags = sorted({self._tag(w) for w in pt.split()})
        return ".".join([_b64(nonce), _b64(ct), *tags])

    def decrypt(self, payload: str) -> str:
        parts = payload.split(".")
        nonce, ct = _unb64(parts[0]), _unb64(parts[1])
        return _aes_ctr(self.k_enc, nonce, ct).decode()

    def trapdoor(self, word: str) -> str:
        """Search token for `word` — hand to the untrusted searcher."""
        return self._tag(word)

    @staticmethod
    def matches(payload: str, trapdoor: str) -> bool:
        """Ciphertext-domain word test — runs without any key.

        Each tag is checked with `hmac.compare_digest` and the scan never
        short-circuits: `trapdoor in tags` would leak which tag slot
        matched (and the length of common prefixes) through timing on the
        untrusted searcher. The leakage profile stays what the scheme
        promises — whether SOME tag equals the trapdoor, nothing more."""
        found = False
        for tag in payload.split(".")[2:]:
            found |= hmac.compare_digest(tag.encode(), trapdoor.encode())
        return found
