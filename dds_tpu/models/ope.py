"""Order-preserving encryption for 32-bit ints (scheme tag "OPE").

Mirrors the role of `hlib.hj.mlib.HomoOpeInt` (`utils/SJHomoLibProvider.scala:
44,55,65`): Int -> Long, strictly monotone, so the proxy can evaluate
range predicates and ordering on ciphertexts alone
(`dds/http/DDSRestServer.scala:541-606, 682-830`).

Construction: with u = x - INT32_MIN (unsigned shift) and a keyed PRF f with
outputs in [0, 2^20):

    enc(x) = u * 2^20 + f(u)

Strictly increasing in x for *any* f since f < 2^20: u1 < u2 implies
u1*S + f(u1) < (u1+1)*S <= u2*S <= enc(x2). Ciphertexts fit in 52 bits
(JSON-safe, "Long" in the reference's wire format). Like all OPE, this
leaks order by design; this construction additionally leaks approximate
magnitude — acceptable for the reference's threat model, and documented.
"""

from __future__ import annotations

import hashlib
import hmac
from dataclasses import dataclass

_SHIFT = 20
_S = 1 << _SHIFT
_I32 = 1 << 31


@dataclass(frozen=True)
class OpeKey:
    key: bytes  # 32 bytes

    def _prf(self, u: int) -> int:
        mac = hmac.new(self.key, u.to_bytes(8, "big"), hashlib.sha256).digest()
        return int.from_bytes(mac[:4], "big") % _S

    def encrypt(self, x: int) -> int:
        if not (-_I32 <= x < _I32):
            raise ValueError("OPE plaintext must fit int32")
        u = x + _I32
        return u * _S + self._prf(u)

    def decrypt(self, c: int) -> int:
        u, rem = divmod(c, _S)
        if not (0 <= u < (1 << 32)) or self._prf(u) != rem:
            raise ValueError("invalid OPE ciphertext")
        return u - _I32
