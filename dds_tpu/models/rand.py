"""Probabilistic (non-homomorphic) encryption for strings (scheme tag "None").

Mirrors the role of `hlib.hj.mlib.HomoRand` / `RandomKeyIv`
(`utils/SJHomoLibProvider.scala:60,70`). Deviation from the reference,
flagged per SURVEY.md §7: the reference reuses one fixed key+IV pair for
every encryption (AES-CBC with a static IV from `client.conf:88`) — a
keystream-reuse bug. We draw a fresh CTR nonce per encryption and carry it
in the ciphertext.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass

from dds_tpu.models._symmetric import aes_ctr, b64d, b64e


@dataclass(frozen=True)
class RandKey:
    key: bytes  # 32 bytes

    def encrypt(self, pt: str) -> str:
        nonce = secrets.token_bytes(16)
        return b64e(nonce + aes_ctr(self.key, nonce, pt.encode()))

    def decrypt(self, ct: str) -> str:
        raw = b64d(ct)
        return aes_ctr(self.key, raw[:16], raw[16:]).decode()
