"""Key material for all six schemes, with a stable JSON serialization.

Replaces the reference's base64 Java-serialized key blobs
(`client.conf:81-88`, loaded at `utils/SJHomoLibProvider.scala:43-50`) with
an explicit, language-neutral format: JSON of hex ints / base64 bytes.
Clients are the only principals who hold these; proxies receive only public
parameters per-request (Paillier n^2, RSA public key), matching the
reference trust model (SURVEY.md §1).
"""

from __future__ import annotations

import json
import secrets
from dataclasses import dataclass

from dds_tpu.models._symmetric import b64d as _unb64, b64e as _b64
from dds_tpu.models.det import DetKey
from dds_tpu.models.mult import RsaMultKey
from dds_tpu.models.ope import OpeKey
from dds_tpu.models.paillier import PaillierKey
from dds_tpu.models.rand import RandKey
from dds_tpu.models.searchable import SearchKey




@dataclass(frozen=True)
class HEKeys:
    ope: OpeKey
    che: DetKey
    lse: SearchKey
    psse: PaillierKey
    mse: RsaMultKey
    none: RandKey

    @staticmethod
    def generate(paillier_bits: int = 2048, rsa_bits: int = 1024) -> "HEKeys":
        return HEKeys(
            ope=OpeKey(secrets.token_bytes(32)),
            che=DetKey(secrets.token_bytes(32), secrets.token_bytes(32)),
            lse=SearchKey(secrets.token_bytes(32), secrets.token_bytes(32)),
            psse=PaillierKey.generate(paillier_bits),
            mse=RsaMultKey.generate(rsa_bits),
            none=RandKey(secrets.token_bytes(32)),
        )

    def to_json(self) -> str:
        return json.dumps(
            {
                "OPE": {"key": _b64(self.ope.key)},
                "CHE": {"k_enc": _b64(self.che.k_enc), "k_mac": _b64(self.che.k_mac)},
                "LSE": {"k_enc": _b64(self.lse.k_enc), "k_tag": _b64(self.lse.k_tag)},
                "PSSE": {"n": hex(self.psse.n), "p": hex(self.psse.p), "q": hex(self.psse.q)},
                "MSE": {
                    "n": hex(self.mse.n),
                    "e": hex(self.mse.e),
                    "d": hex(self.mse.d),
                    "p": hex(self.mse.p),
                    "q": hex(self.mse.q),
                },
                "None": {"key": _b64(self.none.key)},
            }
        )

    @staticmethod
    def from_json(blob: str) -> "HEKeys":
        d = json.loads(blob)
        return HEKeys(
            ope=OpeKey(_unb64(d["OPE"]["key"])),
            che=DetKey(_unb64(d["CHE"]["k_enc"]), _unb64(d["CHE"]["k_mac"])),
            lse=SearchKey(_unb64(d["LSE"]["k_enc"]), _unb64(d["LSE"]["k_tag"])),
            psse=PaillierKey(
                n=int(d["PSSE"]["n"], 16), p=int(d["PSSE"]["p"], 16), q=int(d["PSSE"]["q"], 16)
            ),
            mse=RsaMultKey(
                n=int(d["MSE"]["n"], 16),
                e=int(d["MSE"]["e"], 16),
                d=int(d["MSE"]["d"], 16),
                p=int(d["MSE"]["p"], 16),
                q=int(d["MSE"]["q"], 16),
            ),
            none=RandKey(_unb64(d["None"]["key"])),
        )
