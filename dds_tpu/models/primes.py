"""Prime generation for HE key material.

`cryptography`'s RSA keygen is used for production sizes (>= 1024-bit
modulus); this module supplies Miller-Rabin generation for the smaller
moduli used in fast tests, and is the single place prime logic lives.
"""

from __future__ import annotations

import secrets

_SMALL_PRIMES = [
    2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37, 41, 43, 47, 53, 59, 61, 67,
    71, 73, 79, 83, 89, 97, 101, 103, 107, 109, 113, 127, 131, 137, 139, 149,
]


def is_probable_prime(n: int, rounds: int = 40) -> bool:
    if n < 2:
        return False
    for p in _SMALL_PRIMES:
        if n % p == 0:
            return n == p
    d, r = n - 1, 0
    while d % 2 == 0:
        d //= 2
        r += 1
    for _ in range(rounds):
        a = secrets.randbelow(n - 3) + 2
        x = pow(a, d, n)
        if x in (1, n - 1):
            continue
        for _ in range(r - 1):
            x = x * x % n
            if x == n - 1:
                break
        else:
            return False
    return True


def random_prime(bits: int) -> int:
    """Random prime with exactly `bits` bits (top two bits set, odd)."""
    while True:
        cand = secrets.randbits(bits) | (1 << (bits - 1)) | (1 << (bits - 2)) | 1
        if is_probable_prime(cand):
            return cand


def rsa_primes(modulus_bits: int) -> tuple[int, int]:
    """Two distinct primes whose product has ~modulus_bits bits."""
    half = modulus_bits // 2
    p = random_prime(half)
    while True:
        q = random_prime(modulus_bits - half)
        if q != p:
            return p, q
