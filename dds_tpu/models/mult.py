"""RSA multiplicatively homomorphic encryption (scheme tag "MSE").

Mirrors the behavior the reference consumes from `hlib.hj.mlib.HomoMult`
(`utils/SJHomoLibProvider.scala:59,69`; proxy-side product at
`dds/http/DDSRestServer.scala:479,518`): textbook RSA, where

    enc(m) = m^e mod n,  dec(c) = c^d mod n,  mult = c1 * c2 mod n

so dec(mult(c1, c2)) = m1 * m2 mod n. Deterministic, malleable — that is
the point: the proxy multiplies ciphertexts it cannot read.
"""

from __future__ import annotations

from dataclasses import dataclass

# gated: only key GENERATION at >= 1024 bits uses cryptography's fast RSA
# keygen; without the package the local prime generator takes over
try:
    from cryptography.hazmat.primitives.asymmetric import rsa
except ModuleNotFoundError:  # pragma: no cover - env-dependent
    rsa = None

from dds_tpu.native import powmod


@dataclass(frozen=True)
class RsaMultPublicKey:
    n: int
    e: int = 65537

    def encrypt(self, m: int) -> int:
        return powmod(m % self.n, self.e, self.n)

    def mult(self, c1: int, c2: int) -> int:
        return c1 * c2 % self.n


@dataclass(frozen=True)
class RsaMultKey:
    n: int
    e: int
    d: int
    p: int
    q: int

    @property
    def public(self) -> RsaMultPublicKey:
        return RsaMultPublicKey(self.n, self.e)

    @staticmethod
    def generate(bits: int = 1024) -> "RsaMultKey":
        # Reference ships an RSA-1024 multiplicative key (client.conf:86).
        if bits >= 1024 and rsa is not None:
            priv = rsa.generate_private_key(public_exponent=65537, key_size=bits)
            nums = priv.private_numbers()
            pub = nums.public_numbers
            return RsaMultKey(n=pub.n, e=pub.e, d=nums.d, p=nums.p, q=nums.q)
        from dds_tpu.models.primes import rsa_primes

        e = 65537
        while True:
            p, q = rsa_primes(bits)
            phi = (p - 1) * (q - 1)
            if phi % e:
                return RsaMultKey(n=p * q, e=e, d=pow(e, -1, phi), p=p, q=q)

    def decrypt(self, c: int) -> int:
        # CRT decryption: two half-size modexps. CPython pow, NOT
        # native.powmod: the native runtime memoizes per-modulus
        # Montgomery consts module-wide, and p/q must not outlive this
        # key object (the Sanctum rule, tools/secret_lint.py); per-op
        # RSA decrypt is cheap host math either way.
        mp = pow(c % self.p, self.d % (self.p - 1), self.p)
        mq = pow(c % self.q, self.d % (self.q - 1), self.q)
        qinv = pow(self.q, -1, self.p)
        u = (mp - mq) * qinv % self.p
        return mq + u * self.q
