"""Scheme facade: per-column encrypt/decrypt by scheme tag.

The client-side analogue of the reference's `SJHomoLibProvider` trait
(`utils/SJHomoLibProvider.scala:53-101`): dispatch on the six scheme tags,
plus whole-row encrypt/decrypt against a column-schema list. Fixes the
reference's `until to plainSet.length` off-by-one in encryptFully/
decryptFully (SURVEY.md §7 quirks list) — the variable part here is
`row[until:]`, nothing past the end.

Ciphertext wire types (JSON-safe):
  OPE -> int, PSSE/MSE -> decimal string, CHE/LSE/None -> base64 string.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from dds_tpu.models.keys import HEKeys

SCHEME_TAGS = ("OPE", "LSE", "CHE", "PSSE", "MSE", "None")

# Canonical 8-column schema documented at clt/DDSDataGenerator.scala:11-23
# and configured in client.conf:50-61.
DEFAULT_SCHEMA = ["OPE", "CHE", "PSSE", "MSE", "CHE", "CHE", "CHE", "None"]


@dataclass(frozen=True)
class HomoProvider:
    keys: HEKeys
    # DJN short-exponent obfuscators for PSSE encryption (see
    # PaillierPublicKey.blind_fast): ~5x cheaper per ciphertext on the
    # client, standard variant. False = textbook full-width r^n.
    fast_blinding: bool = True
    # Bulk-ENCRYPTION accelerator (a models.backend.CryptoBackend): when
    # set, precompute_psse_blinds routes the full-width r^n obfuscator
    # modexps through backend.powmod_batch (TPU/native) and PSSE encrypts
    # drain the pool — each ciphertext still gets an independent fresh
    # full-width obfuscator (textbook blinding, strictly stronger than
    # the DJN default), only the modexp moves off the host hot loop.
    # Encrypt-only by construction: r^n works over public parameters; the
    # decrypt legs carry secret CRT moduli and route through
    # secret_backend below instead (never through this object).
    bulk_backend: object = None
    # Sanctum handle (dds_tpu.sanctum.SecretBackend) for the PSSE decrypt
    # CRT legs: None = the host-only default posture; a device-posture
    # handle is the explicit `[crypto] secret-device` opt-in (DEPLOY.md
    # "Secret-material trust boundary (Sanctum)").
    secret_backend: object = None
    _blind_pool: list = field(default_factory=list, repr=False, compare=False)

    @staticmethod
    def generate(paillier_bits: int = 2048, rsa_bits: int = 1024,
                 fast_blinding: bool = True) -> "HomoProvider":
        return HomoProvider(
            HEKeys.generate(paillier_bits, rsa_bits), fast_blinding=fast_blinding
        )

    def precompute_psse_blinds(self, count: int, min_batch: int = 64) -> int:
        """Fill the obfuscator pool for `count` upcoming PSSE encrypts via
        the bulk backend's batched modexp; no-op (returns 0) without a
        backend or below the amortization threshold — per-op paths are
        faster there."""
        if self.bulk_backend is None or count < min_batch:
            return 0
        self._blind_pool.extend(
            self.keys.psse.public.blind_batch(count, self.bulk_backend, min_batch)
        )
        return count

    def encrypt(self, value, tag: str):
        k = self.keys
        match tag:
            case "OPE":
                return k.ope.encrypt(int(value))
            case "LSE":
                return k.lse.encrypt(str(value))
            case "CHE":
                return k.che.encrypt(str(value))
            case "PSSE":
                if self._blind_pool:  # precomputed batch obfuscator
                    return str(
                        k.psse.public.encrypt(int(value), rn=self._blind_pool.pop())
                    )
                if self.fast_blinding:
                    return str(k.psse.public.encrypt_fast(int(value)))
                return str(k.psse.public.encrypt(int(value)))
            case "MSE":
                return str(k.mse.public.encrypt(int(value)))
            case "None":
                return k.none.encrypt(str(value))
            case "Plain":
                # null cipher: deterministic passthrough for AES-less
                # degraded domains (Heliograph's canary schema when the
                # cryptography package is absent) — synthetic plaintexts
                # only, never a substitute for a real scheme on user data
                return str(value)
        raise ValueError(f"unknown scheme tag {tag!r}")

    def decrypt(self, value, tag: str):
        k = self.keys
        match tag:
            case "OPE":
                return k.ope.decrypt(int(value))
            case "LSE":
                return k.lse.decrypt(str(value))
            case "CHE":
                return k.che.decrypt(str(value))
            case "PSSE":
                return k.psse.decrypt_signed(int(value))
            case "MSE":
                return k.mse.decrypt(int(value))
            case "None":
                return k.none.decrypt(str(value))
            case "Plain":
                return str(value)
        raise ValueError(f"unknown scheme tag {tag!r}")

    def encrypt_row(self, row: list, until: int, schema: list[str]) -> list:
        """Encrypt row[:until] per-column by schema, the rest with "None"."""
        fixed = [self.encrypt(v, schema[i]) for i, v in enumerate(row[:until])]
        variable = [self.encrypt(v, "None") for v in row[until:]]
        return fixed + variable

    def decrypt_row(self, row: list, until: int, schema: list[str]) -> list:
        fixed = [self.decrypt(v, schema[i]) for i, v in enumerate(row[:until])]
        variable = [self.decrypt(v, "None") for v in row[until:]]
        return fixed + variable

    def decrypt_rows(self, rows: list[list], until: int, schema: list[str],
                     min_batch: int = 64) -> list[list]:
        """Bulk decrypt_row. All rows' PSSE columns decrypt as ONE
        batched CRT pass on the Sanctum plane (PaillierKey.decrypt_batch
        — the decrypt half of the reference's `decryptFully` hot loop,
        `utils/SJHomoLibProvider.scala:89-101`): host-only unless this
        provider carries a device-posture `secret_backend`. The PUBLIC
        `bulk_backend` is encrypt-only and never sees the decrypt legs;
        other schemes are cheap per-op host work either way."""
        cols = sorted(i for i, s in enumerate(schema[:until]) if s == "PSSE")
        cts = [int(r[i]) for r in rows for i in cols if i < len(r)]
        if len(cts) < min_batch:
            return [self.decrypt_row(r, until, schema) for r in rows]
        k = self.keys.psse
        psse_cols = set(cols)
        plains = iter(
            k.decrypt_batch(cts, backend=self.secret_backend, min_batch=min_batch)
        )
        out = []
        for r in rows:
            dec = []
            for i, v in enumerate(r[:until]):
                if i in psse_cols:
                    dec.append(k.to_signed(next(plains)))
                else:
                    dec.append(self.decrypt(v, schema[i]))
            dec.extend(self.decrypt(v, "None") for v in r[until:])
            out.append(dec)
        return out
