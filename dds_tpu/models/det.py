"""Deterministic comparable encryption for strings (scheme tag "CHE").

Mirrors the role of `hlib.hj.mlib.HomoDet` (`utils/SJHomoLibProvider.scala:
57,67`; proxy equality at `dds/http/DDSRestServer.scala:338,630`): equal
plaintexts yield equal ciphertexts, so the proxy compares ciphertexts by
string equality.

Construction: SIV-style AES — the IV is a PRF of the plaintext, so the
scheme is deterministic yet each distinct plaintext gets a distinct keystream:

    iv = HMAC-SHA256(k_mac, pt)[:16]
    ct = AES-256-CTR(k_enc, iv, pt)
    out = base64(iv || ct)
"""

from __future__ import annotations

import hashlib
import hmac
from dataclasses import dataclass

from dds_tpu.models._symmetric import aes_ctr, b64d, b64e


@dataclass(frozen=True)
class DetKey:
    k_enc: bytes  # 32 bytes
    k_mac: bytes  # 32 bytes

    def encrypt(self, pt: str) -> str:
        data = pt.encode()
        iv = hmac.new(self.k_mac, data, hashlib.sha256).digest()[:16]
        return b64e(iv + aes_ctr(self.k_enc, iv, data))

    def decrypt(self, ct: str) -> str:
        raw = b64d(ct)
        iv, body = raw[:16], raw[16:]
        pt = aes_ctr(self.k_enc, iv, body)
        if hmac.new(self.k_mac, pt, hashlib.sha256).digest()[:16] != iv:
            raise ValueError("invalid CHE ciphertext")
        return pt.decode()

    @staticmethod
    def compare(c1: str, c2: str) -> bool:
        """Ciphertext-domain equality — what the proxy runs.

        Constant-time (`hmac.compare_digest`): both operands are
        attacker-influenced strings compared on the proxy, and a
        short-circuiting `==` would leak the length of the common prefix
        through timing. The scheme's leakage profile is unchanged —
        deterministic encryption reveals equality of ciphertexts by
        design, and equality (plus nothing positional) is still all this
        comparison reveals."""
        return hmac.compare_digest(c1.encode(), c2.encode())
