"""Paillier additively homomorphic encryption (scheme tag "PSSE").

Re-implements the behavior the reference consumes from `hlib.hj.mlib.HomoAdd`
/ `PaillierKey` (`utils/SJHomoLibProvider.scala:58,68`, aggregate folds at
`dds/http/DDSRestServer.scala:385,423`): encrypt/decrypt of integers and
ciphertext-domain addition (modular multiply mod n^2).

Math (g = n + 1 throughout, so g^m = 1 + m*n mod n^2 needs no modexp):

    enc(m; r) = (1 + m*n) * r^n  mod n^2      r random in Z_n*
    dec(c)    = L(c^lambda mod n^2) * mu mod n,  L(x) = (x-1)/n
    add       = c1 * c2 mod n^2
    scalar    = c^k mod n^2

Decryption uses the CRT split over p^2 / q^2 (two half-size modexps instead
of one full-size), the standard Paillier speedup (cf. PAPERS.md
CRT-Paillier), executed on the Sanctum secret-material plane
(`dds_tpu/sanctum`): per-key precomputed constants, host-only by default,
fused two-leg device dispatch behind the explicit `secret-device` opt-in.
"""

from __future__ import annotations

import functools
import secrets
from dataclasses import dataclass
from math import gcd

# gated: only key GENERATION at >= 1024 bits rides cryptography's fast RSA
# keygen; without the package the local prime generator takes over
try:
    from cryptography.hazmat.primitives.asymmetric import rsa
except ModuleNotFoundError:  # pragma: no cover - env-dependent
    rsa = None

from dds_tpu.native import powmod


def _lcm(a: int, b: int) -> int:
    return a // gcd(a, b) * b


# n -> B0 = r0^n mod n^2 for blind_fast (PaillierPublicKey is frozen;
# one fixed random base per key per process is exactly the DJN setup)
_B0_CACHE: dict[int, int] = {}


def _chunked_powmod(backend, bases: list[int], exp: int, mod: int) -> list[int]:
    """backend.powmod_batch in 8192-row chunks: bounds the (rows, L) limb
    allocation per dispatch (~8 MB at L=256) for arbitrarily long batches.
    PUBLIC moduli only (encrypt-side r^n): the backend caches per-modulus
    contexts process-wide, so secret CRT moduli route through
    dds_tpu.sanctum instead (tools/secret_lint.py enforces it)."""
    out: list[int] = []
    for i in range(0, len(bases), 8192):
        out.extend(backend.powmod_batch(bases[i : i + 8192], exp, mod))
    return out


@dataclass(frozen=True)
class PaillierPublicKey:
    n: int

    @property
    def nsquare(self) -> int:
        return self.n * self.n

    def encrypt(self, m: int, r: int | None = None, *, rn: int | None = None) -> int:
        """enc(m; r). `rn` short-circuits the obfuscator with a precomputed
        r^n mod n^2 (`blind()`): bulk encryption then costs one modmul per
        message instead of one n-bit modexp — used by benchmark loaders;
        reusing one rn across messages weakens semantic security, so real
        clients leave it None."""
        n, n2 = self.n, self.nsquare
        m = m % n
        if rn is None:
            if r is None:
                r = self.random_r()
            rn = powmod(r, n, n2)
        # (1 + m n) r^n mod n^2
        return (1 + m * n) % n2 * rn % n2

    def blind(self) -> int:
        """A fresh obfuscator r^n mod n^2 for `encrypt(..., rn=...)`."""
        return powmod(self.random_r(), self.n, self.nsquare)

    def _djn_s_bits(self) -> int:
        """Short-exponent width scaled to the modulus's NIST strength
        estimate (1024->80, 2048->112, 3072->128, 4096->152, 7680->192,
        15360->256 bits): s_bits = 4x strength, floor 320 — 448 at the
        2048-bit default, growing with the key instead of staying fixed."""
        bits = self.n.bit_length()
        # 16 bits of slack: imported keys (he-keys-inline/path) may come
        # from generators that don't force the top bits of p*q, giving a
        # nominally-2048-bit modulus of 2047 bits — that must not silently
        # drop a full strength tier
        for thresh, strength in (
            (15360, 256), (7680, 192), (4096, 152), (3072, 128),
            (2048, 112), (0, 80),
        ):
            if bits >= thresh - 16:
                return max(320, 4 * strength)
        raise AssertionError("unreachable")

    def blind_fast(self, s_bits: int | None = None) -> int:
        """Fresh obfuscator via the Damgard-Jurik-Nielsen short-exponent
        trick: precompute B0 = r0^n mod n^2 once per key, then each
        obfuscator is B0^s for a random `s_bits`-wide s — i.e. (r0^s)^n,
        a valid r^n with r = r0^s. Encryption cost drops from one n-width
        modexp to one s-width modexp (~5x at 2048 bits). Indistinguish-
        ability rests on the standard DJN subgroup argument with
        s_bits >= 2x the security level (default scales with the modulus,
        _djn_s_bits: 448 = 4*112 for 2048-bit n); callers wanting the
        textbook scheme use blind()/encrypt(r=...) — or the
        `client.fast-blinding = false` config knob, which turns this path
        off for the whole client."""
        if s_bits is None:
            s_bits = self._djn_s_bits()
        b0 = _B0_CACHE.get(self.n)
        if b0 is None:
            b0 = powmod(self.random_r(), self.n, self.nsquare)
            _B0_CACHE[self.n] = b0
        s = secrets.randbits(s_bits) | (1 << (s_bits - 1))
        return powmod(b0, s, self.nsquare)

    def encrypt_fast(self, m: int) -> int:
        """enc(m) with a blind_fast() obfuscator (DJN variant, see above)."""
        return self.encrypt(m, rn=self.blind_fast())

    def blind_batch(self, count: int, backend=None, min_batch: int = 64) -> list[int]:
        """`count` fresh FULL-WIDTH obfuscators r^n mod n^2 — textbook
        blinding, each with an independent random r (contrast blind_fast's
        DJN short exponents). A shared n-bit exponent over varying random
        bases is exactly `CryptoBackend.powmod_batch`'s contract: this is
        the encrypt-grade modexp of the reference's client hot loop
        (`utils/SJHomoLibProvider.scala:74-86` encryptFully) routed through
        the batched TPU ladder. Below `min_batch`, or with no backend, a
        host loop (the per-op DJN path stays better for single encrypts)."""
        rs = [self.random_r() for _ in range(count)]
        if backend is not None and count >= min_batch:
            return _chunked_powmod(backend, rs, self.n, self.nsquare)
        n2 = self.nsquare
        return [powmod(r, self.n, n2) for r in rs]

    def encrypt_batch(self, ms: list[int], backend=None, min_batch: int = 64) -> list[int]:
        """Bulk enc(m; r) with per-message full-width obfuscators from
        blind_batch (semantically the textbook scheme, not DJN)."""
        rns = self.blind_batch(len(ms), backend, min_batch)
        return [self.encrypt(m, rn=rn) for m, rn in zip(ms, rns)]

    def random_r(self) -> int:
        n = self.n
        while True:
            r = secrets.randbelow(n - 1) + 1
            if gcd(r, n) == 1:
                return r

    def add(self, c1: int, c2: int) -> int:
        return c1 * c2 % self.nsquare

    def scalar_mul(self, c: int, k: int) -> int:
        return powmod(c, k, self.nsquare)

    def matvec_encode(self, weights) -> list[list[int]]:
        """Encode a SIGNED plaintext weight matrix into Paillier exponent
        residues for ciphertext-side evaluation (the Prism analytics
        plane): Enc(x)^w = Enc(w*x mod n), and a negative weight encodes
        as n - |w| — an exponent congruent to -|w| mod n, so the signed
        decode (`PaillierKey.to_signed`) recovers the negative
        contribution. This is THE encoding site: the REST plane, the
        weighted-fold kernel, and the benchmarks all route through it.

        Rejects |w| >= n (not representable as a distinct residue).
        Decodability of the RESULT is the caller's contract, as for every
        Paillier sum: each row's plaintext W_r . x must stay in
        (-n/2, n/2] or the signed mapping wraps. Note a negative weight's
        encoded exponent is full n-width — a ciphertext-side scalar mult
        by -3 costs a ~n-bit modexp, not a 2-bit one (DEPLOY.md "Encrypted
        analytics")."""
        n = self.n
        out = []
        for row in weights:
            enc = []
            for w in row:
                w = int(w)
                if not -n < w < n:
                    raise ValueError(
                        f"weight magnitude {abs(w).bit_length()} bits "
                        f"exceeds the {n.bit_length()}-bit modulus"
                    )
                enc.append(w % n)
            out.append(enc)
        return out

    def matvec(self, cs: list[int], weights: list[list[int]]) -> list[int]:
        """Host reference for Enc(W @ x): per encoded weight row r
        (`matvec_encode` output), prod_j cs[j]^W[r][j] mod n^2 — one
        modexp per nonzero weight. The batched kernel twin is
        ops/foldmany.fold_weighted; backends pick between them."""
        n2 = self.nsquare
        out = []
        for row in weights:
            acc = 1
            for c, w in zip(cs, row, strict=True):
                if w:
                    acc = acc * powmod(c, w, n2) % n2
            out.append(acc)
        return out


@dataclass(frozen=True)
class PaillierKey:
    """Private key. p, q are the prime factors of n (equal bit length)."""

    n: int
    p: int
    q: int

    @property
    def public(self) -> PaillierPublicKey:
        return PaillierPublicKey(self.n)

    @property
    def nsquare(self) -> int:
        return self.n * self.n

    @staticmethod
    def generate(bits: int = 2048) -> "PaillierKey":
        if bits >= 1024 and rsa is not None:
            # cryptography's RSA keygen produces two same-size primes fast;
            # we only use p and q (it refuses sizes below 1024).
            priv = rsa.generate_private_key(public_exponent=65537, key_size=bits)
            nums = priv.private_numbers()
            p, q = nums.p, nums.q
        else:
            from dds_tpu.models.primes import rsa_primes

            p, q = rsa_primes(bits)
        return PaillierKey(n=p * q, p=p, q=q)

    # -- decryption (CRT) ---------------------------------------------------

    @functools.cached_property
    def _crt(self):
        """Per-key CRT constants (three modular inversions, computed once).
        A cached_property, NOT a module-level cache keyed on the primes:
        the derived secrets live exactly as long as the key object does.
        (cached_property writes the instance __dict__ directly, so it
        works on this frozen dataclass.)"""
        p, q, n = self.p, self.q, self.n
        hp = pow((pow(1 + n, p - 1, p * p) - 1) // p, -1, p)
        hq = pow((pow(1 + n, q - 1, q * q) - 1) // q, -1, q)
        qinv = pow(q, -1, p)
        return hp, hq, qinv


    def decrypt(self, c: int) -> int:
        # the batch-of-one host path IS the per-op CRT decrypt; one body
        return self.decrypt_batch([c])[0]

    def decrypt_batch(self, cs: list[int], backend=None, min_batch: int = 64) -> list[int]:
        """Bulk CRT decrypt on the Sanctum secret-material plane.

        Host-only by default: a per-key plan (`dds_tpu.sanctum`) carries
        the precomputed constants of the batched-CRT optimization
        (PAPERS.md CRT-Paillier) — p^2/q^2, the fixed exponents, the
        native Montgomery consts — stored on THIS key object and
        zeroized with it. This is the "decrypt" half of the north-star's
        "modular exponentiations behind encrypt, decrypt"
        (BASELINE.json), the reference's `decryptFully` loop
        (`utils/SJHomoLibProvider.scala:89-101`).

        `backend` accepts ONLY a Sanctum handle
        (`dds_tpu.sanctum.SecretBackend`). A public-parameter
        `CryptoBackend` raises: routing the secret CRT moduli through
        `powmod_batch` parked p^2/q^2 in `ModCtx.make`'s process-wide
        cache and baked them into persistently-cached executables — p is
        recoverable from p^2 by isqrt (ADVICE.md medium finding; DEPLOY.md
        "Secret-material trust boundary (Sanctum)"). With a
        device-posture handle and >= `min_batch` ciphertexts, both CRT
        legs run as ONE fused batched dispatch (stacked p^2/q^2 lanes,
        per-key exponent digits); below `min_batch` the host plan wins on
        dispatch latency, as for every small batch."""
        from dds_tpu import sanctum

        if backend is not None and not sanctum.is_secret_backend(backend):
            raise ValueError(
                "decrypt_batch no longer accepts public-parameter "
                f"CryptoBackends ({getattr(backend, 'name', type(backend).__name__)!r}): "
                "the CRT legs' moduli p^2/q^2 are secrets and must not "
                "transit ModCtx.make's shared cache or the persistent "
                "compile cache (ADVICE.md). Pass "
                "dds_tpu.sanctum.SecretBackend(device=True) for the "
                "device opt-in, or None for the host-only default."
            )
        if (
            backend is not None
            and getattr(backend, "device", False)
            and len(cs) >= min_batch
        ):
            return sanctum.plan_for(self, backend).decrypt_batch(cs)
        return sanctum.plan_for(self).decrypt_batch(cs)

    def scrub(self) -> None:
        """Eagerly close/zeroize every derived-secret cache this key
        accumulated: the `_crt` constants and any Sanctum plans
        (host consts, device limb arrays, per-plan compiled-fn caches).
        The p/q/n fields themselves are immutable ints — scrub() bounds
        the lifetime of the DERIVED copies; dropping the key object
        finishes the job (a weakref finalizer zeroizes the plans even
        without an explicit scrub)."""
        from dds_tpu import sanctum

        sanctum.scrub_key(self)

    def to_signed(self, m: int) -> int:
        """Map Z_n residues onto the signed range (-n/2, n/2] — the ONE
        signed convention, shared by decrypt_signed, the facade's batched
        row decryption, and the analytics row decoder, and exactly the
        decodability contract `matvec_encode` documents. Pinned as
        `2*m <= n` (keep positive) rather than the earlier floor-division
        comparison, which reads ambiguously at the midpoint under
        even-modulus conventions: (-n/2, n/2] keeps m = n/2 positive."""
        return m if 2 * m <= self.n else m - self.n

    def decrypt_signed(self, c: int) -> int:
        """Decrypt, mapping the upper half of Z_n back to negative ints."""
        return self.to_signed(self.decrypt(c))

    @property
    def lam(self) -> int:
        return _lcm(self.p - 1, self.q - 1)
