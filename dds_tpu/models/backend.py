"""Pluggable ciphertext-arithmetic backends: `cpu` (python ints) and `tpu`.

This is the `crypto.backend` switch from BASELINE.json: the query engine
(proxy) performs all its ciphertext math through this interface, using only
*public* parameters (Paillier n^2, RSA modulus) — never private keys,
matching the reference trust model where `HomoAdd.sum`/`HomoMult.multiply`
run proxy-side on ciphertexts (`dds/http/DDSRestServer.scala:385,423,479`).

The "public parameters only" claim is load-bearing, not aspirational:
every modulus handed to these backends lands in `ModCtx.make`'s
process-wide cache and in executables the persistent compile cache
serializes to disk, so SECRET moduli (the Paillier CRT legs p^2/q^2,
RSA p/q) must never enter — the historical `decrypt_batch(backend=...)`
routing that did exactly that was the ADVICE.md medium finding. Anything
touching key material goes through `dds_tpu.sanctum` instead
(`PaillierKey.decrypt_batch` now refuses these backends outright), and
`tools/secret_lint.py` rejects new flows statically.

The TPU backend converts ciphertext batches to (B, L) limb arrays and runs
the tier-0 Montgomery kernels; a K-term aggregate costs ~1 batched modmul
per term (tree reduction + one domain fixup). The CPU backend is the
baseline the bench compares against.
"""

from __future__ import annotations

from typing import Protocol

import numpy as np

from dds_tpu.ops import bignum as bn
from dds_tpu.ops.montgomery import ModCtx


class CryptoBackend(Protocol):
    """Ciphertext-domain modular arithmetic over PUBLIC parameters only
    (secret moduli: dds_tpu.sanctum — see the module docstring)."""

    name: str

    def modmul(self, c1: int, c2: int, modulus: int) -> int: ...

    def modmul_fold(self, cs: list[int], modulus: int) -> int: ...

    def powmod_batch(self, bases: list[int], exp: int, modulus: int) -> list[int]: ...

    def matvec(
        self, cs: list[int], weights: list[list[int]], modulus: int,
        rows: object = None,
    ) -> list[int]: ...


def _host_matvec(
    cs: list[int], weights: list[list[int]], modulus: int, powmod=pow
) -> list[int]:
    """Per-row weighted fold on host ints: out[r] = prod_j cs[j]^w[r][j]
    mod modulus, skipping zero weights (the common case for GroupBySum
    selector rows). Shared by every backend's below-crossover path."""
    out = []
    for row in weights:
        acc = 1
        for c, w in zip(cs, row):
            if w:
                acc = acc * powmod(c, w, modulus) % modulus
        out.append(acc)
    return out


class CpuBackend:
    """Python-int reference backend (the CPU baseline of BASELINE.md)."""

    name = "cpu"

    def modmul(self, c1: int, c2: int, modulus: int) -> int:
        return c1 * c2 % modulus

    def modmul_fold(self, cs: list[int], modulus: int) -> int:
        acc = 1
        for c in cs:
            acc = acc * c % modulus
        return acc

    def powmod_batch(self, bases: list[int], exp: int, modulus: int) -> list[int]:
        return [pow(b, exp, modulus) for b in bases]

    def matvec(
        self, cs: list[int], weights: list[list[int]], modulus: int,
        rows: object = None,
    ) -> list[int]:
        # `rows` (pre-gathered device limbs, Lodestone) is a device-path
        # optimization; the host loop works from the ints either way
        return _host_matvec(cs, weights, modulus)


def _use_pallas() -> bool:
    """Compiled Pallas kernels on real TPU; jnp reference path elsewhere.

    Override with DDS_PALLAS=1 (force, incl. interpret mode on CPU) or
    DDS_PALLAS=0 (force the jnp path even on TPU).
    """
    import os

    flag = os.environ.get("DDS_PALLAS", "").strip().lower()
    if flag:
        return flag not in ("0", "false", "off", "no")
    import jax

    return jax.default_backend() == "tpu"


class TpuBackend:
    """Batched limb-tensor backend on the tier-0 Montgomery kernels.

    On a real TPU the fused Pallas CIOS kernels run (ops/pallas_mont);
    elsewhere (XLA-CPU in tests) the portable jnp path. Compiled kernels
    are cached per modulus via ModCtx.make's lru_cache.
    """

    name = "tpu"

    def __init__(self, pallas: bool | None = None, min_device_batch: int | None = None,
                 kernel: str | None = None, mesh=None):
        import os

        self.pallas = _use_pallas() if pallas is None else pallas
        # Kernel family for folds AND batch modexp: "v2" = schoolbook
        # product + MXU band-matmul REDC (ops/mont_mxu), "v1" = fused CIOS
        # (ops/pallas_mont). v2 wins both ops on TPU hardware (see
        # benchmarks/kernel_compare.py); DDS_KERNEL overrides both.
        self.kernel = (
            kernel if kernel is not None else os.environ.get("DDS_KERNEL", "v2")
        ).strip().lower()
        if self.kernel not in ("v1", "v2"):
            raise ValueError(
                f"unknown fold kernel {self.kernel!r} (must be v1 or v2)"
            )
        if self.pallas and self.kernel == "v2":
            # surface a bogus DDS_KARATSUBA at construction, not deep
            # inside the first traced fold; only v2 consults it, and
            # ops.flags is jax-free (no pallas import on this path)
            from dds_tpu.ops.flags import karatsuba_mode

            karatsuba_mode()
        # Adaptive dispatch: below this fold width the flat device-dispatch
        # latency loses to a host fold, so small aggregates stay on host
        # (measured crossover ~1024 on tunneled v5e; DDS_TPU_MIN_BATCH
        # overrides, 0 forces everything onto the device).
        self.min_device_batch = (
            int(os.environ.get("DDS_TPU_MIN_BATCH", "1024"))
            if min_device_batch is None
            else min_device_batch
        )
        import threading

        # Multi-chip scale-out (SURVEY.md §5.7-5.8): with a jax.sharding
        # Mesh, folds/modexps shard the ciphertext axis over the devices via
        # parallel/mesh.py (limb chains stay device-local; ONE all_gather
        # combines partial products over ICI). Pass mesh= explicitly or set
        # DDS_MESH=N to build an N-device mesh lazily at first use.
        self.mesh = mesh
        self._mesh_n = (
            int(os.environ.get("DDS_MESH", "0")) if mesh is None else 0
        )

        self._stores: dict[int, object] = {}
        self._stores_lock = threading.Lock()  # folds run on proxy threads

    @staticmethod
    def _host_fold(cs: list[int], modulus: int) -> int:
        # native.fold's contract: never fails (python-int fallback inside)
        from dds_tpu import native

        return native.fold(cs, modulus)

    def store_for(self, modulus: int):
        """Per-modulus device-resident cipher store (ops/store.py)."""
        with self._stores_lock:
            store = self._stores.get(modulus)
            if store is None:
                from dds_tpu.ops.store import DeviceCipherStore

                ctx = ModCtx.make(modulus)
                store = DeviceCipherStore(
                    modulus, reduce=lambda rows: self.reduce_mul_device(ctx, rows)
                )
                self._stores[modulus] = store
            return store

    def modmul_fold_resident(self, cs: list[int], modulus: int) -> int:
        """Fold via the device store: unseen ciphertexts ingest once, the
        aggregate gathers resident rows on-device. Folds narrower than
        min_device_batch run on host (the store is not consulted: a later
        wide aggregate pays ingest for those rows then)."""
        if len(cs) < self.min_device_batch:
            return self._host_fold(cs, modulus)
        return self.store_for(modulus).fold(cs)

    def modmul(self, c1: int, c2: int, modulus: int) -> int:
        # one multiply: a device round-trip can never win
        return c1 * c2 % modulus

    def _mesh_kernel(self) -> str:
        """The single kernel-family rule for every composite fold path —
        mesh-sharded (parallel/mesh.py), coalesced (ops/foldmany) AND
        resident-fused (dds_tpu/resident): the SAME family the
        single-chip path would use (v1/v2 when pallas is on, the portable
        jnp scans otherwise), so scale-out and batching never silently
        run a slower kernel."""
        return self.kernel if self.pallas else "jnp"

    def fold_kernel(self) -> str:
        """Public alias of the composite-fold kernel rule — what the
        Lodestone ResidentPlane builds its fused dispatch on."""
        return self._mesh_kernel()

    def resident_plane(self, initial_rows: int = 256,
                       max_rows: int = 1 << 20):
        """A Lodestone ResidentPlane wired to THIS backend's kernel
        family, mesh, and per-pool reduce — so lone-group resident folds
        and fused sharded folds run exactly the kernels the flat paths
        would (one dispatch rule, one kernel rule)."""
        from dds_tpu.resident import ResidentPlane

        def reduce_factory(modulus: int):
            ctx = ModCtx.make(modulus)
            return lambda rows: self.reduce_mul_device(ctx, rows)

        return ResidentPlane(
            kernel=self.fold_kernel(),
            mesh=self._get_mesh(),
            initial_rows=initial_rows,
            max_rows=max_rows,
            reduce_factory=reduce_factory,
        )

    def _get_mesh(self):
        if self.mesh is None and self._mesh_n > 1:
            from dds_tpu.parallel.mesh import make_mesh

            self.mesh = make_mesh(self._mesh_n)
            self._mesh_n = 0
        return self.mesh

    def reduce_mul_device(self, ctx: ModCtx, batch):
        """Modular product over an already-resident (K, L) limb batch.

        The device-level fold entry point shared by modmul_fold, the
        proxy's aggregate routes, and bench.py — one dispatch rule."""
        mesh = self._get_mesh()
        if mesh is not None and mesh.devices.size > 1:
            from dds_tpu.parallel import mesh as pm

            return pm.sharded_reduce_mul_fixed(
                ctx, batch, mesh, kernel=self._mesh_kernel()
            )
        if self.pallas:
            if self.kernel == "v2":
                from dds_tpu.ops import mont_mxu

                return mont_mxu.reduce_mul2(mont_mxu.MxuCtx.make(ctx), batch)
            from dds_tpu.ops import pallas_mont

            return pallas_mont.reduce_mul(ctx, batch)
        return ctx.reduce_mul(batch)

    def modmul_fold(self, cs: list[int], modulus: int) -> int:
        if len(cs) < self.min_device_batch:
            return self._host_fold(cs, modulus)
        ctx = ModCtx.make(modulus)
        batch = bn.ints_to_batch(cs, ctx.L)
        out = self.reduce_mul_device(ctx, batch)
        return bn.limbs_to_int(np.asarray(out)[0])

    def modmul_fold_many(self, folds: list[list[int]], modulus: int) -> list[int]:
        """Fold R requests' operand lists in ONE device dispatch
        (ops/foldmany): the cross-request batching for concurrent small
        aggregates that individually sit below min_device_batch."""
        from dds_tpu.ops import foldmany

        return foldmany.fold_many(folds, modulus, kernel=self._mesh_kernel())

    def matvec(
        self, cs: list[int], weights: list[list[int]], modulus: int,
        rows: object = None,
    ) -> list[int]:
        """Plaintext-matrix x ciphertext-vector products (Prism / PC-MM):
        one batched weighted-fold dispatch (ops/foldmany.fold_weighted)
        when the R*K cell count clears the device crossover; below it the
        host loop wins for the same dispatch-latency reason small
        aggregates do. `rows` optionally supplies the operands as
        already-gathered device limbs from a Lodestone resident pool, so
        the device path skips host int -> limb marshaling entirely."""
        if len(weights) * len(cs) < self.min_device_batch:
            from dds_tpu.native import powmod

            return _host_matvec(cs, weights, modulus, powmod=powmod)
        from dds_tpu.ops import foldmany

        return foldmany.fold_weighted(
            cs, weights, modulus, kernel=self._mesh_kernel(), rows=rows
        )

    def powmod_batch(self, bases: list[int], exp: int, modulus: int) -> list[int]:
        ctx = ModCtx.make(modulus)
        batch = bn.ints_to_batch(bases, ctx.L)
        mesh = self._get_mesh()
        if mesh is not None and mesh.devices.size > 1:
            from dds_tpu.ops.montgomery import _exp_to_digits
            from dds_tpu.parallel import mesh as pm

            D = mesh.devices.size
            B = len(bases)
            padded = -(-B // D) * D
            if padded != B:  # pad with base 1 (1^e = 1), slice after
                import jax.numpy as jnp

                one = np.zeros((padded - B, ctx.L), np.uint32)
                one[:, 0] = 1
                batch = jnp.concatenate([jnp.asarray(batch), jnp.asarray(one)], 0)
            out = pm.sharded_pow_mod(
                ctx, batch, _exp_to_digits(exp), mesh, kernel=self._mesh_kernel()
            )
            return bn.batch_to_ints(np.asarray(out)[:B])
        if self.pallas:
            if self.kernel == "v2":
                # v2 wins modexp in both regimes (benchmarks/kernel_compare,
                # back-to-back on a v5e: sustained 7.5 vs 12.7 ms, single
                # dispatch 48 vs 84 ms @ B=256/L=256/64-bit exp)
                from dds_tpu.ops import mont_mxu

                out = mont_mxu.pow_mod2(mont_mxu.MxuCtx.make(ctx), batch, exp)
            else:
                from dds_tpu.ops import pallas_mont

                out = pallas_mont.pow_mod(ctx, batch, exp)
        else:
            out = ctx.pow_mod(batch, exp)
        return bn.batch_to_ints(np.asarray(out))


class NativeBackend:
    """Host-side C++ CIOS backend (dds_tpu.native) — the accelerated CPU
    path for hosts without a TPU; falls back to python ints if the native
    library is unavailable."""

    name = "native"

    def modmul(self, c1: int, c2: int, modulus: int) -> int:
        return c1 * c2 % modulus

    def modmul_fold(self, cs: list[int], modulus: int) -> int:
        from dds_tpu import native

        return native.fold(cs, modulus)

    def powmod_batch(self, bases: list[int], exp: int, modulus: int) -> list[int]:
        from dds_tpu import native

        return native.powmod_batch(bases, exp, modulus)

    def matvec(
        self, cs: list[int], weights: list[list[int]], modulus: int,
        rows: object = None,
    ) -> list[int]:
        from dds_tpu.native import powmod

        return _host_matvec(cs, weights, modulus, powmod=powmod)


_BACKENDS = {"cpu": CpuBackend, "tpu": TpuBackend, "native": NativeBackend}


def get_backend(name: str) -> CryptoBackend:
    try:
        return _BACKENDS[name]()
    except KeyError:
        raise ValueError(f"unknown crypto backend {name!r} (have {sorted(_BACKENDS)})")
