"""Pluggable ciphertext-arithmetic backends: `cpu` (python ints) and `tpu`.

This is the `crypto.backend` switch from BASELINE.json: the query engine
(proxy) performs all its ciphertext math through this interface, using only
*public* parameters (Paillier n^2, RSA modulus) — never private keys,
matching the reference trust model where `HomoAdd.sum`/`HomoMult.multiply`
run proxy-side on ciphertexts (`dds/http/DDSRestServer.scala:385,423,479`).

The TPU backend converts ciphertext batches to (B, L) limb arrays and runs
the tier-0 Montgomery kernels; a K-term aggregate costs ~1 batched modmul
per term (tree reduction + one domain fixup). The CPU backend is the
baseline the bench compares against.
"""

from __future__ import annotations

from typing import Protocol

import numpy as np

from dds_tpu.ops import bignum as bn
from dds_tpu.ops.montgomery import ModCtx


class CryptoBackend(Protocol):
    """Ciphertext-domain modular arithmetic over public parameters."""

    name: str

    def modmul(self, c1: int, c2: int, modulus: int) -> int: ...

    def modmul_fold(self, cs: list[int], modulus: int) -> int: ...

    def powmod_batch(self, bases: list[int], exp: int, modulus: int) -> list[int]: ...


class CpuBackend:
    """Python-int reference backend (the CPU baseline of BASELINE.md)."""

    name = "cpu"

    def modmul(self, c1: int, c2: int, modulus: int) -> int:
        return c1 * c2 % modulus

    def modmul_fold(self, cs: list[int], modulus: int) -> int:
        acc = 1
        for c in cs:
            acc = acc * c % modulus
        return acc

    def powmod_batch(self, bases: list[int], exp: int, modulus: int) -> list[int]:
        return [pow(b, exp, modulus) for b in bases]


class TpuBackend:
    """Batched limb-tensor backend on the tier-0 Montgomery kernels.

    Works on whatever JAX's default platform is (the real TPU chip in
    deployment; XLA-CPU in tests). Compiled kernels are cached per modulus
    via ModCtx.make's lru_cache.
    """

    name = "tpu"

    def modmul(self, c1: int, c2: int, modulus: int) -> int:
        return self.modmul_fold([c1, c2], modulus)

    def modmul_fold(self, cs: list[int], modulus: int) -> int:
        ctx = ModCtx.make(modulus)
        batch = bn.ints_to_batch(cs, ctx.L)
        out = ctx.reduce_mul(batch)
        return bn.limbs_to_int(np.asarray(out)[0])

    def powmod_batch(self, bases: list[int], exp: int, modulus: int) -> list[int]:
        ctx = ModCtx.make(modulus)
        batch = bn.ints_to_batch(bases, ctx.L)
        return bn.batch_to_ints(np.asarray(ctx.pow_mod(batch, exp)))


_BACKENDS = {"cpu": CpuBackend, "tpu": TpuBackend}


def get_backend(name: str) -> CryptoBackend:
    try:
        return _BACKENDS[name]()
    except KeyError:
        raise ValueError(f"unknown crypto backend {name!r} (have {sorted(_BACKENDS)})")
