"""Tier-1 homomorphic / property-preserving encryption schemes.

Same six scheme tags as the reference's closed-source crypto jar
(`utils/SJHomoLibProvider.scala:22-27`, `lib/README.txt`), re-implemented
from scratch:

| tag  | scheme                         | module        |
|------|--------------------------------|---------------|
| PSSE | Paillier (additive HE)         | paillier.py   |
| MSE  | RSA multiplicative HE          | mult.py       |
| OPE  | order-preserving encryption    | ope.py        |
| CHE  | deterministic (comparable)     | det.py        |
| LSE  | word-searchable encryption     | searchable.py |
| None | probabilistic AES              | rand.py       |

The modular arithmetic behind PSSE/MSE runs on the tier-0 batched Montgomery
kernels when the `tpu` backend is selected (see backend.py); all schemes also
have a pure-CPU path used by clients and as the benchmark baseline.
"""

from dds_tpu.models.keys import HEKeys  # noqa: F401
from dds_tpu.models.facade import HomoProvider, SCHEME_TAGS  # noqa: F401
from dds_tpu.models.backend import get_backend  # noqa: F401
