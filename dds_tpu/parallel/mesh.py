"""Mesh-sharded ciphertext arithmetic: the multi-chip scale-out path.

The reference's only parallelism is replication fan-out over Akka remoting
(SURVEY.md §2, "Parallelism inventory"); the TPU-native analogue is
data-parallel batched ciphertext arithmetic sharded over a device mesh
(SURVEY.md §5.7-5.8):

- the K axis (ciphertexts) is sharded across devices ("batch/limb
  parallelism": each ciphertext's limb chain stays device-local so carries
  and Montgomery reductions never cross the interconnect);
- aggregates reduce locally per shard, then combine partial products with
  ONE small collective (`all_gather` of (D, L) partials — modular product
  is not an add, so `psum` does not apply) and a replicated log2(D) tail
  reduction.

The shard-local math runs the SAME kernel family the single-chip path
uses (`kernel=`): "v2" = VPU product + MXU band-REDC (ops/mont_mxu),
"v1" = fused CIOS Pallas (ops/pallas_mont), "jnp" = the portable scan
kernels — so N chips mean N x the fast kernel, not N x the portable one.
Only the O(D) combine (D-1 multiplies of one residue each) stays on the
portable `_mont_mul_raw`: a Pallas dispatch per single-row multiply would
pad 1 lane to a full tile and cost more than it saves.

Works identically on a real TPU slice and on the test fabric
(`--xla_force_host_platform_device_count`, Pallas in interpret mode).
"""

from __future__ import annotations

import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dds_tpu.ops import bignum as bn
from dds_tpu.ops.montgomery import ModCtx, _mont_mul_raw, _mont_exp_raw, _tree_reduce_raw

KERNELS = ("jnp", "v1", "v2")


def make_mesh(n_devices: int | None = None, axis: str = "batch") -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def _check_kernel(kernel: str) -> str:
    if kernel not in KERNELS:
        raise ValueError(f"unknown mesh kernel {kernel!r} (have {KERNELS})")
    return kernel


def group_sharding(mesh: Mesh | None, index: int, axis: str = "batch"):
    """NamedSharding pinning one shard group's resident pool (Lodestone,
    dds_tpu/resident) to its slice of the mesh: group `index` maps round-
    robin onto the mesh's devices, and the pool's (rows, L) buffer lives
    wholly on that device via a one-device sub-mesh + replicated
    PartitionSpec — so the fused sharded fold gathers each group's rows
    where they already are. None (no mesh, or a single device — the test
    fabric) means default placement: exactly the pre-Lodestone buffer."""
    if mesh is None or mesh.devices.size <= 1:
        return None
    dev = mesh.devices.flat[index % mesh.devices.size]
    return NamedSharding(Mesh(np.array([dev]), (axis,)), P())


# jitted shard_map executables, keyed by (op, modulus, mesh, axis, kernel):
# the serving path calls these per aggregate request, and rebuilding the
# closure each call would defeat jax.jit's trace cache (jit keys on
# function identity + shapes). Bounded FIFO (like ModCtx.make's lru_cache):
# on the serving path the modulus comes from the client-supplied `nsqr`
# query param, and each new modulus costs an XLA compile + retained
# executable — unbounded growth would be a client-driven memory/compile DoS.
_FN_CACHE: dict = {}
_FN_CACHE_MAX = 64
# folds are dispatched from proxy worker threads (asyncio.to_thread), so
# eviction + insert must be atomic or two threads can pop the same FIFO key
_FN_CACHE_LOCK = threading.Lock()


def _fn_cache_put(key, fn) -> None:
    with _FN_CACHE_LOCK:
        while len(_FN_CACHE) >= _FN_CACHE_MAX:
            _FN_CACHE.pop(next(iter(_FN_CACHE)), None)
        _FN_CACHE[key] = fn


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _halving_tree_lm(mul_lm, x):
    """Power-of-two tree fold over the lane axis of limbs-major x (L, W):
    repeatedly multiply the left half by the right half with `mul_lm`
    until one lane remains. Shared by both Pallas kernel families here
    (and the same shape as mont_mxu._reduce2_fn's in-jit tree)."""
    w = x.shape[1]
    while w > 1:
        h = w // 2
        x = mul_lm(x[:, :h], x[:, h : 2 * h])
        w = h
    return x


def _local_fold_fn(ctx: ModCtx, kernel: str, interpret: bool):
    """Shard-local tree fold: (P2, L) batch-major -> (1, L) partial product
    (times R^-(P2-1)), on the configured kernel family."""
    if kernel == "v2":
        from dds_tpu.ops import mont_mxu

        mctx = mont_mxu.MxuCtx.make(ctx)
        karatsuba = mont_mxu._use_karatsuba()
        mul = lambda a, b: mont_mxu.mul2_lm(mctx, a, b, interpret, karatsuba)
        return lambda local: _halving_tree_lm(mul, local.T).T
    if kernel == "v1":
        from dds_tpu.ops import pallas_mont

        mul = lambda a, b: pallas_mont.mul_lm(ctx, a, b, interpret=interpret)
        return lambda local: _halving_tree_lm(mul, local.T).T

    N = jnp.asarray(ctx.N)
    n0inv = jnp.uint32(ctx.n0inv)
    one_mont = jnp.asarray(ctx.one_mont)

    def fold(local):
        return _tree_reduce_local(local, N, n0inv, one_mont)

    return fold


def _tree_reduce_local(cs, N, n0inv, one_mont):
    """Tree reduction (shard-local, no collectives), any leaf count.

    Odd levels are padded with the Montgomery identity R mod n. The R-power
    accounting is structure-independent: a tree over K real leaves plus any
    number of identity pads yields prod * R^-(K-1) (each pad contributes a
    factor R, each internal mont_mul a factor R^-1, and pads - internals =
    -(K-1) always).
    """
    t = cs
    while t.shape[0] > 1:
        if t.shape[0] % 2:
            t = jnp.concatenate([t, one_mont[None, :]], axis=0)
        t = _mont_mul_raw(t[0::2], t[1::2], N, n0inv)
    return t


def combine_partials(partials, modulus: int) -> int:
    """Modular-product tail combine over already-reduced partials — the
    host-integer twin of the replicated log2(D) tree `sharded_reduce_mul`
    runs over gathered per-device partials (`_tree_reduce_local`). The
    Constellation scatter-gather path (http/server._fold_aggregate) uses
    it to merge per-shard aggregate folds: every shard group shares one
    Paillier modulus, and the modular product is associative/commutative,
    so S per-shard partials combine bit-for-bit to the single-shard
    result regardless of how the keyspace was partitioned. Kept here, not
    duplicated in shard/, so the two partial-combine paths stay one
    implementation site."""
    parts = [p % modulus for p in partials]
    if not parts:
        raise ValueError("combine_partials needs at least one partial")
    while len(parts) > 1:
        nxt = [
            (parts[i] * parts[i + 1]) % modulus
            for i in range(0, len(parts) - 1, 2)
        ]
        if len(parts) % 2:
            nxt.append(parts[-1])
        parts = nxt
    return parts[0]


def sharded_reduce_mul(ctx: ModCtx, cs, mesh: Mesh, axis: str = "batch",
                       ring: bool = False, kernel: str = "jnp"):
    """Modular product of K ciphertexts sharded over `mesh`.

    cs: (K, L) plain-domain, K divisible by mesh size times 1 (padded here
    to a power of two per shard with the Montgomery identity, like
    ModCtx.reduce_mul). Returns (1, L) = prod(cs) * R^-(K-1) mod n,
    replicated; callers fix the R power exactly as ModCtx.reduce_mul does.
    `kernel` picks the shard-local fold family (module docstring).

    Two combine collectives, same result and R accounting (D partials,
    D-1 montgomery multiplies either way):
    - ring=False: ONE all_gather of the (D, L) partials + a replicated
      tail tree — best here because the payload is tiny (L limbs/device);
    - ring=True: D-1 `ppermute` neighbor hops, each device multiplying the
      partial circulating past it — the ring-attention-style ICI pattern
      that wins when per-device payloads are large enough that an
      all_gather would burst-buffer D copies at once.
    """
    _check_kernel(kernel)
    D = mesh.devices.size
    K = cs.shape[0]
    shard = -(-K // D)
    P2 = 1 << max(0, (shard - 1).bit_length())
    total = P2 * D
    if total != K:
        pad = jnp.broadcast_to(jnp.asarray(ctx.one_mont), (total - K, ctx.L))
        cs = jnp.concatenate([jnp.asarray(cs), pad], axis=0)

    # NOT keyed on P2: jit retraces per input shape under one cache entry,
    # and nothing in the closure bakes the shard width — keying on it would
    # fragment the bounded FIFO per request size and churn compiles
    key = ("reduce", ctx.n, mesh, axis, ring, kernel)
    fn = _FN_CACHE.get(key)
    if fn is None:
        N = jnp.asarray(ctx.N)
        n0inv = jnp.uint32(ctx.n0inv)
        one_mont = jnp.asarray(ctx.one_mont)
        perm = [(d, (d + 1) % D) for d in range(D)]
        local_fold = _local_fold_fn(ctx, kernel, _interpret_default())

        def step(local):
            # local: (P2, L) on each device
            partial = local_fold(local)                           # (1, L)
            if ring:
                def hop(_, acc_msg):
                    acc, msg = acc_msg
                    msg = jax.lax.ppermute(msg, axis, perm)
                    return _mont_mul_raw(acc, msg, N, n0inv), msg

                acc, _ = jax.lax.fori_loop(
                    0, D - 1, hop, (partial, partial)
                )
                return acc  # equal on every device after D-1 hops
            partials = jax.lax.all_gather(partial, axis, tiled=True)  # (D, L)
            return _tree_reduce_local(partials, N, n0inv, one_mont)   # (1, L) replicated

        fn = jax.jit(
            jax.shard_map(
                step,
                mesh=mesh,
                in_specs=P(axis),
                out_specs=P(),  # replicated result
                check_vma=False,  # scan carries start replicated inside the shard
            )
        )
        _fn_cache_put(key, fn)
    return fn(cs)


def sharded_reduce_mul_fixed(ctx: ModCtx, cs, mesh: Mesh, axis: str = "batch",
                             ring: bool = False, kernel: str = "jnp"):
    """Like ModCtx.reduce_mul but mesh-sharded: returns prod(cs) mod n (1, L)."""
    K = cs.shape[0]
    prod = sharded_reduce_mul(ctx, cs, mesh, axis, ring, kernel)
    R = 1 << (bn.LIMB_BITS * ctx.L)
    fix = bn.int_to_limbs(pow(R % ctx.n, K, ctx.n), ctx.L)
    return ctx.mont_mul(prod, jnp.asarray(fix)[None, :])


def sharded_pow_mod(ctx: ModCtx, bases, exp_digits, mesh: Mesh,
                    axis: str = "batch", kernel: str = "jnp"):
    """Batched modexp with the batch axis sharded across the mesh.

    bases: (B, L) plain domain, B divisible by mesh size. exp_digits:
    (E,) uint32 4-bit MSB-first digits, replicated. Purely data-parallel —
    zero collectives; each device exponentiates its shard on the
    configured kernel family.
    """
    _check_kernel(kernel)
    E = int(exp_digits.shape[0])
    # E is in the key only for v2: _pow2_body bakes `E > 1` into the trace;
    # the jnp/v1 steps derive everything from the digits' runtime shape, so
    # one entry per modulus serves every exponent width there
    key = ("pow", ctx.n, mesh, axis, kernel, E if kernel == "v2" else None)
    fn = _FN_CACHE.get(key)
    if fn is None:
        interpret = _interpret_default()
        if kernel == "v2":
            from dds_tpu.ops import mont_mxu

            mctx = mont_mxu.MxuCtx.make(ctx)
            body = mont_mxu._pow2_body(
                mctx, E, interpret, mont_mxu._use_karatsuba()
            )

            def step(local_bases, digits):
                return body(local_bases, digits.astype(jnp.int32))
        elif kernel == "v1":
            from dds_tpu.ops import pallas_mont

            R2col = jnp.asarray(ctx.R2)[:, None]
            one = np.zeros((ctx.L, 1), np.uint32)
            one[0, 0] = 1
            one = jnp.asarray(one)

            def step(local_bases, digits):
                x = local_bases.T                              # (L, B)
                xm = pallas_mont.mul_lm(
                    ctx, x, jnp.broadcast_to(R2col, x.shape), interpret=interpret
                )
                r = pallas_mont.exp_lm(
                    ctx, xm, digits.astype(jnp.int32), interpret=interpret
                )
                out = pallas_mont.mul_lm(
                    ctx, r, jnp.broadcast_to(one, r.shape), interpret=interpret
                )
                return out.T
        else:
            N = jnp.asarray(ctx.N)
            n0inv = jnp.uint32(ctx.n0inv)
            R2 = jnp.asarray(ctx.R2)
            one_mont = jnp.asarray(ctx.one_mont)
            one_plain = np.zeros((ctx.L,), np.uint32)
            one_plain[0] = 1
            one_plain = jnp.asarray(one_plain)

            def step(local_bases, digits):
                mont = _mont_mul_raw(
                    local_bases, jnp.broadcast_to(R2, local_bases.shape), N, n0inv
                )
                r = _mont_exp_raw(mont, digits, one_mont, N, n0inv)
                return _mont_mul_raw(r, jnp.broadcast_to(one_plain, r.shape), N, n0inv)

        fn = jax.jit(
            jax.shard_map(
                step,
                mesh=mesh,
                in_specs=(P(axis), P()),
                out_specs=P(axis),
                check_vma=False,  # scan carries start replicated inside the shard
            )
        )
        _fn_cache_put(key, fn)
    return fn(bases, jnp.asarray(exp_digits))
