"""Multi-chip parallelism: mesh-sharded ciphertext batch operations."""

from dds_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    sharded_reduce_mul,
    sharded_pow_mod,
)
