"""Proxy-side ABD access: nonce-challenged, HMAC-verified quorum reads/writes.

Counterpart of the `fetchSet` / `writeSet` functions inside the reference
proxy (`dds/http/DDSRestServer.scala:952-1000, 1002-1050`): pick a random
trusted replica as coordinator, send a signed `Envelope(IRead/IWrite)`,
await the enveloped reply, and verify (a) the challenge nonce is the request
nonce + increment, (b) the proxy HMAC over the reply, (c) the echoed key.
Every protocol violation increments local suspicion on the coordinator
(3 strikes excludes it permanently — `utils/TrustedNodesList.scala:23-29`)
and raises a typed Byzantine exception; mere timeouts instead trip a
per-coordinator circuit breaker (utils/retry.CircuitBreaker) that steers
the next picks elsewhere and self-heals via half-open probes, so replicas
cut off by a (healed) partition regain coordination without a restart.
Callers may pass a `Deadline` so each attempt's timeout shrinks to the
remaining request budget instead of a fixed 5 s per layer.

Reply correlation mirrors Akka ask semantics: a junk reply from the asked
coordinator (wrong shape, bare message) resolves the outstanding request and
is then rejected by validation, rather than stalling until timeout.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from typing import Optional

from dds_tpu.core import messages as M
from dds_tpu.core.errors import (
    AllBreakersOpenError,
    ByzFailedNonceChallengeError,
    ByzInvalidKeyError,
    ByzInvalidSignatureError,
    ByzUnknownReplyError,
    WrongShardError,
)
from dds_tpu.core.transport import Transport
from dds_tpu.obs import context as obs_context
from dds_tpu.obs.metrics import metrics
from dds_tpu.utils.retry import CircuitBreaker, Deadline, DeadlineExceededError
from dds_tpu.utils.trace import tracer
from dds_tpu.utils import sigs
from dds_tpu.utils.trust import TrustedNodesList

log = logging.getLogger("dds.quorum_client")

# vote marker: "this replica's whole tag vector equals the caller's
# fingerprinted cached vector" (see read_tags)
_UNCHANGED = object()


@dataclass
class AbdClientConfig:
    proxy_mac_secret: bytes = b"rest2abd"
    nonce_increment: int = 1
    request_timeout: float = 5.0
    supervisor: str | None = None  # only accept ActiveReplicas from here
    # read_tags broadcasts ReadTagBatch to the replicas itself and verifies
    # each reply's intranet MAC, so it needs the ABD secret + quorum size
    # (the proxy lives inside the intranet in the reference too —
    # `dds-system.conf:94` puts both secrets in the one shared config)
    abd_mac_secret: bytes = b"intranet-abd-secret"
    quorum_size: int = 5
    # per-coordinator circuit breaker (utils/retry.CircuitBreaker): transient
    # unreachability (ask timeouts) trips it and self-heals via half-open
    # probes, while cryptographic protocol violations ALSO land on the
    # permanent 3-strike suspicion counter. Splitting the two is what lets
    # a healed partition serve again without a proxy restart.
    breaker_threshold: int = 3
    breaker_reset: float = 2.0
    # Constellation shard label for this client's metric series (empty =
    # unsharded, series keep their historical label sets)
    shard: str = ""
    # Bulwark fast-fail (core/admission): when EVERY trusted coordinator's
    # breaker is open and none will half-open within the caller's
    # remaining Deadline budget, raise AllBreakersOpenError immediately
    # instead of burning the budget on attempts that are provably futile.
    # The guard is deliberately that narrow: while a probe still fits the
    # budget, the degraded try (which may close a breaker) proceeds as
    # before, so nothing heals slower.
    fast_fail_all_open: bool = True
    # Atlas read-local leases (dds_tpu/geo): when enabled and an in-region
    # replica is known, reads first try a single-hop LocalRead against the
    # TTL-leased holder; any refusal, timeout, or validation failure drops
    # the lease session and the read falls back to the full cross-region
    # quorum path below — leases are a latency optimisation, never a
    # correctness dependency.
    lease_enabled: bool = False
    region: str = ""  # this proxy's home region ("" = geo-unaware)
    # replica addr (or bare name) -> region label, as placed by shard.fabric
    replica_regions: Optional[dict] = None
    lease_ttl: float = 2.0
    lease_renew_margin: float = 0.5  # renew when lease remaining < margin
    local_read_timeout: float = 0.75  # LocalRead budget before fallback


class AbdClient:
    def __init__(
        self,
        addr: str,
        net: Transport,
        replicas: list[str],
        config: AbdClientConfig | None = None,
    ):
        self.addr = addr
        self.net = net
        self.cfg = config or AbdClientConfig()
        self.replicas = TrustedNodesList(replicas)
        # coordinator addr -> CircuitBreaker (created on first failure path)
        self.breakers: dict[str, CircuitBreaker] = {}
        # challenge nonce -> (future, coordinator)
        self._pending: dict[int, tuple[asyncio.Future, str]] = {}
        self._preferred: list[str] = []  # supervisor's freshest-half view
        # tag-broadcast nonce -> (future, sender->tags votes, digest, keys,
        # request fingerprint | None)
        self._pending_tags: dict[int, tuple] = {}
        # Constellation: when a ShardRouter owns this client it installs a
        # supplier for the ACTIVE map epoch; every Envelope/ReadTagBatch is
        # stamped with it so replicas can fence stale routes. None = -1 =
        # unsharded (replicas without a shard state ignore the field).
        self.shard_epoch: Optional[callable] = None
        # Atlas lease session: {"target", "replica", "token", "renew_at",
        # "expires"} while we hold an in-region read lease, else None.
        # Client-side expiry is measured from SEND time, so it is always
        # conservative w.r.t. the holder's table clock.
        self._lease: Optional[dict] = None
        self._lease_retry_at = 0.0  # grant backoff after a refusal/timeout
        # lease/local-read request nonce -> future (replies echo it)
        self._pending_lease: dict[int, asyncio.Future] = {}
        self._now = time.monotonic  # test hook (fake-clock schedules)
        net.register(addr, self.handle)

    async def handle(self, sender: str, msg) -> None:
        if isinstance(msg, M.Envelope) and msg.nonce in self._pending:
            fut, _ = self._pending[msg.nonce]
            if not fut.done():
                fut.set_result(msg)
            return
        if isinstance(msg, M.TagBatchReply) and msg.nonce in self._pending_tags:
            self._on_tag_batch_reply(sender, msg)
            return
        if isinstance(msg, M.WrongShard):
            # shard fence rejection: resolve the matching outstanding
            # request (Envelope ops correlate by challenge nonce, tag
            # batches by request nonce). Handled BEFORE the junk-reply
            # fallthrough — a fence from a replica that also coordinates
            # another in-flight op must not resolve THAT op as junk and
            # earn the honest replica a suspicion strike.
            if msg.nonce in self._pending:
                fut, _ = self._pending[msg.nonce]
                if not fut.done():
                    fut.set_result(msg)
            elif msg.nonce in self._pending_tags:
                self._on_wrong_shard_batch(sender, msg)
            return
        if isinstance(msg, (M.LeaseGrant, M.LocalReadReply)):
            # correlate by REQUEST nonce (like TagBatchReply). Unmatched
            # (late) lease replies are dropped HERE — they must never fall
            # through to the junk-reply path and strike an honest replica
            # that also coordinates an outstanding Envelope op.
            entry = self._pending_lease.get(msg.nonce)
            if entry is not None and not entry.done():
                entry.set_result(msg)
            return
        if isinstance(msg, M.ActiveReplicas):
            if self.cfg.supervisor is not None and sender != self.cfg.supervisor:
                log.warning("ignoring ActiveReplicas from non-supervisor %s", sender)
                return
            if msg.replicas:
                # the supervisor serves only the freshest HALF of the active
                # list (coordinator load-balancing, DDSRestServer.scala:139-147)
                # — merge, don't reset: broadcasts (read_tags) need the whole
                # quorum membership, which a partial view must not shrink
                self.replicas.merge(msg.replicas)
                self._preferred = list(msg.replicas)
            return
        # junk from a coordinator we are waiting on resolves that request
        # (Akka-ask semantics); validation will reject it.
        for nonce, (fut, coord) in list(self._pending.items()):
            if coord == sender and not fut.done():
                fut.set_result(msg)
                return
        log.debug("unmatched message from %s: %s", sender, type(msg).__name__)

    def _breaker(self, node: str) -> CircuitBreaker:
        b = self.breakers.get(node)
        if b is None:
            b = self.breakers[node] = CircuitBreaker(
                self.cfg.breaker_threshold, self.cfg.breaker_reset,
                name=node.rsplit("/", 1)[-1],
            )
        return b

    def breaker_states(self) -> dict[str, str]:
        """Current breaker state per coordinator (for the /health route)."""
        return {n: b.state for n, b in sorted(self.breakers.items())}

    def breaker_census(self) -> tuple[int, list[float]]:
        """(trusted coordinator count, half-open ETAs of the ones whose
        breaker currently refuses traffic) — the breaker-health signal the
        Bulwark shedding controller and the Retry-After derivation read."""
        trusted = self.replicas.get_trusted()
        etas = []
        for n in trusted:
            b = self.breakers.get(n)
            if b is not None and not b.allow():
                etas.append(b.half_open_eta())
        return len(trusted), etas

    def min_half_open_eta(self) -> float | None:
        """Nearest half-open probe among refusing breakers (None = no
        breaker is refusing, or none exist)."""
        _, etas = self.breaker_census()
        positive = [e for e in etas if e > 0]
        return min(positive) if positive else None

    def _coord_failed(self, coord: str) -> None:
        """A coordinator answered with a PROTOCOL VIOLATION: permanent
        suspicion strike (cryptographic evidence, never decays) plus a
        breaker failure (steers the next pick away immediately)."""
        self.replicas.increment_suspicion(coord)
        metrics.inc(
            "dds_coordinator_violations_total", node=coord.rsplit("/", 1)[-1],
            help="protocol violations observed per coordinator",
        )
        tracer.event("abd.coordinator_violation", node=coord)
        self._breaker(coord).record_failure()

    def _mlabels(self, **labels) -> dict:
        """Metric labels, plus the shard label when this client serves one
        group of a constellation (unsharded series stay label-stable)."""
        if self.cfg.shard:
            labels["shard"] = self.cfg.shard
        return labels

    def _epoch(self) -> int:
        return self.shard_epoch() if self.shard_epoch is not None else -1

    def _check_wrong_shard(self, reply, coord: str, key: str, challenge: int):
        """Validate a WrongShard fence reply for an Envelope op. A valid
        fence raises WrongShardError (no suspicion — the replica behaved
        correctly); a forged one is a protocol violation like any other."""
        if not isinstance(reply, M.WrongShard):
            return
        cfg = self.cfg
        if (
            reply.nonce != challenge
            or reply.key != key
            or not sigs.validate_proxy_signature(
                cfg.proxy_mac_secret, reply.key, reply.nonce, reply.signature,
                ["wrong-shard", reply.epoch],
            )
        ):
            self._coord_failed(coord)
            raise ByzInvalidSignatureError(coord)
        self._breaker(coord).record_success()
        raise WrongShardError(key, replica_epoch=reply.epoch,
                              sent_epoch=self._epoch())

    @staticmethod
    def _note_verify(op: str, t0: float) -> None:
        """Record reply-HMAC verification as its own `abd.verify` span —
        Chronoscope's hmac-verify stage, carved out of quorum-rtt so crypto
        cost is never misread as network cost."""
        cur = obs_context.current()
        tracer.record(
            "abd.verify", (time.perf_counter() - t0) * 1e3,
            _ctx=obs_context.child(cur) if cur is not None else None, op=op,
        )

    def _attempt_timeout(self, deadline: Optional[Deadline]) -> float:
        """Per-attempt timeout, clipped to the caller's remaining budget."""
        if deadline is None:
            return self.cfg.request_timeout
        timeout = deadline.timeout(self.cfg.request_timeout)
        if timeout <= 0:
            raise DeadlineExceededError(
                f"no budget left for a quorum attempt ({deadline!r})",
                elapsed=deadline.elapsed(),
            )
        return timeout

    async def _ask(self, call, nonce: int, signature: bytes, exclude=(),
                   deadline: Optional[Deadline] = None, op: str = "ask"):
        # route around open breakers; defer_to falls back to the full
        # trusted set when everything is excluded (a degraded try beats
        # instant failure, and a success closes the breaker again)
        blocked = tuple(n for n, b in self.breakers.items() if not b.allow())
        self._maybe_fast_fail(blocked, deadline, op)
        timeout = self._attempt_timeout(deadline)
        coordinator = self.replicas.defer_to(
            tuple(exclude) + blocked, prefer=self._preferred
        )
        challenge = nonce + self.cfg.nonce_increment
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[challenge] = (fut, coordinator)
        t0 = time.perf_counter()
        try:
            self.net.send(
                self.addr, coordinator,
                M.Envelope(call, nonce, signature, epoch=self._epoch()),
            )
            try:
                reply = await asyncio.wait_for(fut, timeout)
            except asyncio.TimeoutError:
                metrics.inc(
                    "dds_quorum_timeouts_total", **self._mlabels(
                        op=op, node=coordinator.rsplit("/", 1)[-1],
                    ),
                    help="quorum rounds that timed out per coordinator",
                )
                # transient unreachability: breaker only — the permanent
                # suspicion counter is reserved for protocol violations, so
                # a healed partition's replicas regain coordination without
                # a restart (deviation from the reference, which struck on
                # every timeout and could never un-strike)
                self._breaker(coordinator).record_failure()
                raise
            metrics.observe(
                "dds_quorum_rtt_seconds", time.perf_counter() - t0,
                **self._mlabels(op=op),
                help="proxy->coordinator quorum round-trip time",
            )
            return reply, coordinator, challenge
        finally:
            self._pending.pop(challenge, None)

    def _maybe_fast_fail(self, blocked: tuple, deadline: Optional[Deadline],
                         op: str) -> None:
        """Bulwark fast-fail: when EVERY trusted coordinator's breaker is
        refusing traffic and the nearest half-open probe lies beyond the
        caller's remaining budget, no attempt in this request can succeed
        — each would time out against a target the breaker already ruled
        out, and the budget cannot outlive the earliest probe. Degrade NOW
        with the typed error (microseconds) instead of burning the
        Deadline. While any probe still fits the budget the degraded try
        proceeds exactly as before."""
        if not self.cfg.fast_fail_all_open or deadline is None:
            return
        trusted = self.replicas.get_trusted()
        if not trusted or any(n not in blocked for n in trusted):
            return
        eta = min(self.breakers[n].half_open_eta() for n in trusted)
        if eta < deadline.remaining():
            return
        metrics.inc(
            "dds_fast_fail_total", **self._mlabels(op=op),
            help="requests degraded instantly: all coordinator breakers "
                 "open past the remaining budget",
        )
        tracer.event("abd.fast_fail", op=op, eta=round(eta, 4),
                     targets=len(trusted))
        raise AllBreakersOpenError(eta, len(trusted))

    async def fetch_set(self, key: str, deadline: Optional[Deadline] = None):
        """Quorum read; returns the stored set (list) or None."""
        return (await self.fetch_set_tagged(key, deadline=deadline))[0]

    async def fetch_set_tagged(self, key: str, deadline: Optional[Deadline] = None):
        """Quorum read; returns (set|None, tag) — the tag of the value the
        coordinator wrote back, for tag-validated caching."""
        value, tag, _ = await self.fetch_set_attributed(key, deadline=deadline)
        return value, tag

    async def fetch_set_attributed(self, key: str, exclude=(),
                                   deadline: Optional[Deadline] = None):
        """Quorum read; returns (set|None, tag, coordinator). `exclude`
        steers coordinator choice away from given nodes so an audit's
        corroborating re-read goes through a different coordinator than
        the read it is checking. `deadline` clips the attempt to the
        caller's remaining budget."""
        nonce = sigs.generate_nonce()
        sig = sigs.proxy_signature(self.cfg.proxy_mac_secret, key, nonce)
        # validation runs INSIDE the span so a committed op's span carries
        # its audit facts (ok/key/tag) — the Watchtower auditor
        # (obs/watchtower) scopes each op's quorum participants to this
        # span's subtree and checks per-key tag monotonicity from the
        # annotated tag; a failed attempt records the span without `ok`
        # and is never audited as a commit.
        cfg = self.cfg
        with tracer.span("abd.fetch") as span_meta:
            # Atlas fast path: one hop to the in-region lease holder.
            # Skipped when the caller steers coordinators (`exclude` means
            # an audit wants an INDEPENDENT quorum read, not a lease echo);
            # any miss falls through to the quorum round below.
            if cfg.lease_enabled and not exclude:
                local = await self._local_fetch(key, span_meta, deadline)
                if local is not None:
                    return local
            reply, coord, challenge = await self._ask(
                M.IRead(key), nonce, sig, exclude, deadline, op="fetch"
            )
            span_meta["coordinator"] = coord
            self._check_wrong_shard(reply, coord, key, challenge)

            match reply:
                case M.Envelope(M.IReadReply(k, value, tag), rnonce, rsig):
                    if rnonce != challenge:
                        self._coord_failed(coord)
                        raise ByzFailedNonceChallengeError(coord)
                    t_v = time.perf_counter()
                    verified = sigs.validate_proxy_signature(
                        cfg.proxy_mac_secret, k, rnonce, rsig,
                        [value, sigs.tag_payload(tag)],
                    )
                    self._note_verify("read", t_v)
                    if not verified:
                        self._coord_failed(coord)
                        raise ByzInvalidSignatureError(coord)
                    if k != key:
                        self._coord_failed(coord)
                        raise ByzInvalidKeyError(coord)
                    self._breaker(coord).record_success()
                    span_meta["ok"] = True
                    span_meta["op"] = "read"
                    span_meta["key"] = key
                    if tag is not None:
                        span_meta["seq"] = tag.seq
                        span_meta["tag_id"] = tag.id
                    return value, tag, coord
                case _:
                    self._coord_failed(coord)
                    raise ByzUnknownReplyError(coord)

    async def write_set(self, key: str, value,
                        deadline: Optional[Deadline] = None) -> str:
        """Quorum write (value=None removes); returns the key on success."""
        return (await self.write_set_tagged(key, value, deadline=deadline))[0]

    async def write_set_tagged(self, key: str, value,
                               deadline: Optional[Deadline] = None):
        """Quorum write; returns (key, tag) where tag is the tag written."""
        nonce = sigs.generate_nonce()
        sig = sigs.proxy_signature(self.cfg.proxy_mac_secret, key, nonce, value)
        cfg = self.cfg
        with tracer.span("abd.write") as span_meta:
            reply, coord, challenge = await self._ask(
                M.IWrite(key, value), nonce, sig, (), deadline, op="write"
            )
            span_meta["coordinator"] = coord
            self._check_wrong_shard(reply, coord, key, challenge)

            match reply:
                case M.Envelope(M.IWriteReply(k, tag), rnonce, rsig):
                    if rnonce != challenge:
                        self._coord_failed(coord)
                        raise ByzFailedNonceChallengeError(coord)
                    t_v = time.perf_counter()
                    verified = sigs.validate_proxy_signature(
                        cfg.proxy_mac_secret, k, rnonce, rsig,
                        sigs.tag_payload(tag),
                    )
                    self._note_verify("write", t_v)
                    if not verified:
                        self._coord_failed(coord)
                        raise ByzInvalidSignatureError(coord)
                    if k != key:
                        self._coord_failed(coord)
                        raise ByzInvalidKeyError(coord)
                    self._breaker(coord).record_success()
                    span_meta["ok"] = True
                    span_meta["op"] = "write"
                    span_meta["key"] = key
                    if tag is not None:
                        span_meta["seq"] = tag.seq
                        span_meta["tag_id"] = tag.id
                    return k, tag
                case _:
                    self._coord_failed(coord)
                    raise ByzUnknownReplyError(coord)

    # ------------------------------------------------- Atlas read-local leases

    def _local_replica(self) -> Optional[str]:
        """The trusted in-region replica eligible to hold our read lease
        (first in trusted order — deterministic for seeded fleets)."""
        cfg = self.cfg
        if not cfg.lease_enabled or not cfg.region or not cfg.replica_regions:
            return None
        for addr in self.replicas.get_trusted():
            name = addr.rsplit("/", 1)[-1]
            region = cfg.replica_regions.get(
                addr, cfg.replica_regions.get(name, ""))
            if region == cfg.region:
                return addr
        return None

    def lease_state(self) -> Optional[dict]:
        """Current lease session for /health: {replica, remaining} or None."""
        lease = self._lease
        if lease is None:
            return None
        remaining = lease["expires"] - self._now()
        if remaining <= 0:
            return None
        return {"replica": lease["replica"], "region": self.cfg.region,
                "remaining": round(remaining, 3)}

    def invalidate_lease(self) -> None:
        """Drop the lease session; the next read goes full-quorum (and may
        re-acquire after the grant backoff)."""
        self._lease = None

    async def _ask_lease(self, target: str, msg, nonce: int, timeout: float):
        """One lease-plane round trip, correlated by request nonce."""
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending_lease[nonce] = fut
        try:
            self.net.send(self.addr, target, msg)
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._pending_lease.pop(nonce, None)

    async def _ensure_lease(self) -> Optional[dict]:
        """Grant-or-renew the region read lease. None = no lease available
        right now (no in-region replica, refusal, or inside the backoff)."""
        cfg = self.cfg
        lease, now = self._lease, self._now()
        if lease is not None and now < lease["renew_at"]:
            return lease
        if lease is None and now < self._lease_retry_at:
            return None
        target = self._local_replica()
        if target is None:
            self._lease = None
            return None
        nonce = sigs.generate_nonce()
        sig = sigs.manifest_signature(
            cfg.abd_mac_secret, "lease-request",
            {"region": cfg.region, "ttl": cfg.lease_ttl}, nonce)
        sent_at = now
        try:
            grant = await self._ask_lease(
                target, M.LeaseRequest(cfg.region, cfg.lease_ttl, nonce, sig),
                nonce, cfg.local_read_timeout)
        except asyncio.TimeoutError:
            grant = None
        if (
            not isinstance(grant, M.LeaseGrant)
            or not grant.ok
            or grant.region != cfg.region
            or not sigs.validate_manifest_signature(
                cfg.abd_mac_secret, "lease-grant",
                {"region": grant.region, "replica": grant.replica,
                 "token": grant.token, "expires": grant.expires,
                 "ok": grant.ok}, nonce, grant.signature)
        ):
            self._lease = None
            self._lease_retry_at = self._now() + cfg.lease_renew_margin
            metrics.inc(
                "dds_geo_lease_failures_total", **self._mlabels(),
                help="lease grant/renew attempts that were refused, "
                     "timed out, or failed validation",
            )
            return None
        # expiry measured from SEND time: always conservative vs the
        # holder's own table clock, so we stop using the token strictly
        # before the holder stops honouring it
        self._lease = {
            "target": target,
            "replica": grant.replica,
            "token": grant.token,
            "renew_at": sent_at + cfg.lease_ttl - cfg.lease_renew_margin,
            "expires": sent_at + cfg.lease_ttl,
        }
        return self._lease

    async def _local_fetch(self, key: str, span_meta: dict,
                           deadline: Optional[Deadline]):
        """Lease fast path for one read: single hop to the in-region
        holder. Returns (value, tag, holder) or None — None means "take
        the full quorum path", never an error."""
        cfg = self.cfg
        lease = await self._ensure_lease()
        if lease is None:
            return None
        timeout = cfg.local_read_timeout
        if deadline is not None:
            timeout = min(timeout, deadline.remaining())
            if timeout <= 0:
                return None
        nonce = sigs.generate_nonce()
        sig = sigs.proxy_signature(cfg.proxy_mac_secret, key, nonce,
                                   ["local-read", cfg.region])
        t0 = time.perf_counter()
        try:
            reply = await self._ask_lease(
                lease["target"],
                M.LocalRead(key, cfg.region, lease["token"], nonce, sig,
                            epoch=self._epoch()),
                nonce, timeout)
        except asyncio.TimeoutError:
            # holder unreachable: drop the session (the table-side TTL
            # unpins the group's quorums on its own) and go full-quorum
            self._lease = None
            self._lease_retry_at = self._now() + cfg.lease_renew_margin
            metrics.inc(
                "dds_geo_local_read_fallbacks_total",
                **self._mlabels(reason="timeout"),
                help="lease reads that fell back to a full quorum round",
            )
            return None
        if (
            not isinstance(reply, M.LocalReadReply)
            or reply.key != key
            or not sigs.validate_proxy_signature(
                cfg.proxy_mac_secret, reply.key, reply.nonce, reply.signature,
                [reply.ok, reply.value,
                 sigs.tag_payload(reply.tag) if reply.tag is not None
                 else None])
        ):
            # a garbled/forged local reply is cryptographic evidence like
            # any other protocol violation
            self.replicas.increment_suspicion(lease["target"])
            self._lease = None
            metrics.inc(
                "dds_geo_local_read_fallbacks_total",
                **self._mlabels(reason="invalid"),
                help="lease reads that fell back to a full quorum round",
            )
            return None
        if not reply.ok:
            # typed refusal: the lease was revoked/expired table-side (or
            # the key is fenced) — degrade to full quorum immediately
            self._lease = None
            self._lease_retry_at = self._now() + cfg.lease_renew_margin
            metrics.inc(
                "dds_geo_local_read_fallbacks_total",
                **self._mlabels(reason="refused"),
                help="lease reads that fell back to a full quorum round",
            )
            return None
        metrics.observe(
            "dds_quorum_rtt_seconds", time.perf_counter() - t0,
            **self._mlabels(op="local_read"),
            help="proxy->coordinator quorum round-trip time",
        )
        span_meta["ok"] = True
        span_meta["op"] = "read"
        span_meta["key"] = key
        # Watchtower reads these two: `lease` switches the span from the
        # strict quorum-intersection bound to the documented lease-window
        # invariant, `replica` is what the lease_lookup is checked against
        span_meta["lease"] = True
        span_meta["replica"] = lease["replica"]
        if reply.tag is not None:
            span_meta["seq"] = reply.tag.seq
            span_meta["tag_id"] = reply.tag.id
        return reply.value, reply.tag, lease["target"]

    def _on_wrong_shard_batch(self, sender: str, msg: M.WrongShard) -> None:
        """A replica fenced a ReadTagBatch: the whole round fails with
        WrongShardError (the router re-partitions against a fresh map). A
        forged fence earns the sender a suspicion strike instead."""
        fut, _, _, keys, _ = self._pending_tags[msg.nonce]
        if fut.done():
            return
        if (
            msg.key not in keys
            or not sigs.validate_proxy_signature(
                self.cfg.proxy_mac_secret, msg.key, msg.nonce, msg.signature,
                ["wrong-shard", msg.epoch],
            )
        ):
            self.replicas.increment_suspicion(sender)
            return
        fut.set_exception(WrongShardError(
            msg.key, replica_epoch=msg.epoch, sent_epoch=self._epoch()
        ))

    def _on_tag_batch_reply(self, sender: str, msg: M.TagBatchReply) -> None:
        fut, votes, digest, keys, fp = self._pending_tags[msg.nonce]
        if fut.done() or sender in votes:
            return
        if msg.unchanged:
            # "my vector equals the fingerprint you sent": only meaningful
            # when we sent one and it matches; MAC covers (fp, digest, nonce)
            if (
                fp is None
                or msg.fingerprint != fp
                or msg.digest != digest
                or not sigs.validate_abd_batch_unchanged_signature(
                    self.cfg.abd_mac_secret, fp, msg.digest, msg.nonce,
                    msg.signature,
                )
            ):
                self.replicas.increment_suspicion(sender)
                return
            votes[sender] = _UNCHANGED
        else:
            if (
                msg.digest != digest
                or len(msg.tags) != len(keys)
                or not sigs.validate_abd_batch_signature(
                    self.cfg.abd_mac_secret, msg.tags, msg.digest, msg.nonce,
                    msg.signature,
                )
            ):
                self.replicas.increment_suspicion(sender)
                return
            votes[sender] = tuple(msg.tags)
        if len(votes) >= self.cfg.quorum_size:
            fut.set_result(list(votes.values()))

    async def read_tags(
        self,
        keys: list[str],
        digest: str | None = None,
        fingerprint: bytes | None = None,
        cached_tags: list | None = None,
        deadline: Optional[Deadline] = None,
    ) -> list[M.ABDTag]:
        """Batched freshness probe: the quorum-max tag per key via ONE
        tag-only round broadcast by the proxy ITSELF — `ReadTagBatch` fans
        out to every trusted replica, each reply's intranet MAC is verified
        here, and the per-key max is taken over the first `quorum_size`
        valid reply vectors. No single coordinator is trusted: any quorum
        intersects a completed write's quorum in an honest replica, so the
        max can never be deflated below the newest completed write's tag —
        a lying replica can only inflate it, forcing a spurious re-fetch,
        never a stale serve. That argument keys votes by SENDER, so it is
        only as strong as the transport's sender authenticity: in-process
        delivery (InMemoryNet) or per-node mutual TLS on TcpNet; a shared
        frame secret alone does not stop a credentialed replica from
        stuffing the vote with spoofed senders. Cheap because no set
        contents travel — the cache-validation primitive behind the
        proxy's aggregate cache.

        Steady-state fast path: pass `fingerprint` (sha256 of `cached_tags`
        via sigs.tags_fingerprint) and replicas whose vector matches answer
        `unchanged` without shipping K tags; an unchanged vote stands for
        `cached_tags` itself in the quorum max (fingerprint equality is
        vector equality). Deflation-resistance is unchanged — a replica
        hiding a newer completed write behind a false `unchanged` is
        outvoted by the honest quorum-intersection replica, whose full
        reply carries the higher tag. What an unchanged echo DOES hand a
        credentialed liar is a way to confirm the caller's cached vector
        without knowing it — relevant only when that vector already holds
        a tag a Byzantine coordinator planted, a forgery the planter could
        always confirm itself; the caller's audit (not this round) is what
        bounds that class either way. `digest` may be passed in when the
        caller already computed the keys digest (it is part of the request
        MAC either way)."""
        trusted = self.replicas.get_trusted()
        if len(trusted) < self.cfg.quorum_size:
            raise ByzUnknownReplyError(
                f"only {len(trusted)} trusted replicas < quorum {self.cfg.quorum_size}"
            )
        if fingerprint is not None and cached_tags is None:
            raise ValueError("fingerprint requires cached_tags")
        # the broadcast needs quorum_size replies, so a fabric whose every
        # coordinator breaker is open past the budget is as futile here as
        # for a point op — same fast-fail
        self._maybe_fast_fail(
            tuple(n for n, b in self.breakers.items() if not b.allow()),
            deadline, "read_tags",
        )
        timeout = self._attempt_timeout(deadline)
        nonce = sigs.generate_nonce()
        if digest is None:
            digest = sigs.key_from_set(list(keys))
        sig = sigs.proxy_signature(self.cfg.proxy_mac_secret, digest, nonce)
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending_tags[nonce] = (fut, {}, digest, tuple(keys), fingerprint)
        try:
            with tracer.span("abd.read_tags", k=len(keys)):
                t0 = time.perf_counter()
                req = M.ReadTagBatch(tuple(keys), nonce, sig, fingerprint,
                                     epoch=self._epoch())
                for replica in trusted:
                    self.net.send(self.addr, replica, req)
                vectors = await asyncio.wait_for(fut, timeout)
                metrics.observe(
                    "dds_quorum_rtt_seconds", time.perf_counter() - t0,
                    **self._mlabels(op="read_tags"),
                    help="proxy->coordinator quorum round-trip time",
                )
            if not keys:
                return []
            if all(v is _UNCHANGED for v in vectors):
                # return the caller's own list BY IDENTITY: callers use
                # `result is cached_tags` as the all-fresh signal
                return cached_tags
            expanded = [
                cached_tags if v is _UNCHANGED else v for v in vectors
            ]
            return [max(col) for col in zip(*expanded)]
        finally:
            self._pending_tags.pop(nonce, None)

    def refresh_from(self, supervisor: str) -> None:
        """Ask the supervisor for the freshest active replicas (fire & forget;
        the `ActiveReplicas` reply lands in `handle`)."""
        self.net.send(self.addr, supervisor, M.RequestReplicas())
