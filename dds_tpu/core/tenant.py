"""Tenant identity at the trust boundary: validate before anything keys on it.

The ``x-dds-tenant`` header is wire input that used to flow RAW into
admission bucket labels — and with Bastion it flows into keyring lookups,
pool striping, and metric labels, all of which are dictionaries keyed by
the value. This module is the single clamp every consumer goes through:

- absent / empty header → ``DEFAULT_TENANT`` (single-tenant deployments
  never notice tenancy exists);
- well-formed ids (``[A-Za-z0-9][A-Za-z0-9._-]{0,63}``) pass through;
- anything else — control bytes, quotes, over-length, leading
  punctuation — raises the typed `TenantError`, which the REST edge maps
  to a 400 (never a silent fallback: a garbled id that fell back to
  "default" would silently read another tenant's keyspace).

The charset is the conservative DNS-label-plus-dots alphabet: safe in
metric label values, file names, JSON, and log lines without escaping.
"""

from __future__ import annotations

import re

__all__ = ["DEFAULT_TENANT", "CANARY_TENANT", "TENANT_RE", "MAX_TENANT_LEN",
           "TenantError", "validate_tenant"]

DEFAULT_TENANT = "default"
MAX_TENANT_LEN = 64
TENANT_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")

# Heliograph's reserved canary keyspace. The leading underscore is
# REJECTED by TENANT_RE for everyone else, which is exactly the point:
# no wire-supplied tenant id can ever collide with (or squat on) the
# canary keyspace; only the explicit carve-out below admits it. Canary
# traffic is clamped like any tenant but excluded from user-facing
# analytics, per-tenant SLO attribution, and admission fairness — see
# http/server.py and obs/heliograph.py.
CANARY_TENANT = "__heliograph__"


class TenantError(ValueError):
    """Typed 400: the tenant header is present but malformed."""

    def __init__(self, raw: str, reason: str):
        super().__init__(f"invalid tenant id: {reason}")
        self.raw = raw
        self.reason = reason


def validate_tenant(raw: str | None) -> str:
    """Clamp a wire-supplied tenant header to a safe identifier.

    Returns `DEFAULT_TENANT` for None/empty, the id itself when valid,
    and raises `TenantError` otherwise.
    """
    if raw is None:
        return DEFAULT_TENANT
    value = raw.strip()
    if not value:
        return DEFAULT_TENANT
    if value == CANARY_TENANT:
        # the one id allowed to break the leading-character rule: the
        # prober's own requests arrive through the same REST edge
        return value
    if len(value) > MAX_TENANT_LEN:
        raise TenantError(value[:MAX_TENANT_LEN] + "...",
                          f"longer than {MAX_TENANT_LEN} chars")
    if not TENANT_RE.match(value):
        raise TenantError(value, "must match [A-Za-z0-9][A-Za-z0-9._-]*")
    return value
