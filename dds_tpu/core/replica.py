"""BFT-ABD replica: quorum-replicated register with HMAC auth + anti-replay.

Counterpart of `dds/core/BFTABDNode.scala` — same three behaviors
(healthy / sentinent / byzantine), same two-phase quorum protocol, same
suspicion triggers — re-expressed as a plain async message handler over the
`core.transport` fabric instead of an Akka actor.

Protocol summary (healthy):
- proxy `Envelope(IWrite)` -> broadcast `ReadTag`; on quorum of `TagReply`
  take max tag, bump seq, broadcast `Write`; on quorum of `WriteAck` answer
  the proxy with `IWriteReply` under challenge nonce = client nonce + inc.
- proxy `Envelope(IRead)` -> broadcast `Read`; on quorum of `ReadReply`
  take max (tag, value, signature), broadcast write-back `Write` with the
  *original* signature; on quorum of `WriteAck` answer `IReadReply`.
- every inbound protocol message is HMAC-verified and nonce-replay-checked;
  violations raise `Suspect` votes to the supervisor
  (`BFTABDNode.scala:137,158,165,212,219,250,298,319,326`).

Deviations (documented per SURVEY.md §7): tags order by (seq, id) rather
than seq-with-arbitrary-tie-break; the ABD HMAC signs the true `tag.seq`
(reference signs `seq + 1`, `Utils.scala:33`).
"""

from __future__ import annotations

import logging
import random
from dataclasses import dataclass, field

from dds_tpu.core import messages as M
from dds_tpu.core.antientropy import AntiEntropy, MerkleIndex
from dds_tpu.core.transport import Transport
from dds_tpu.obs.flight import flight
from dds_tpu.obs.metrics import metrics
from dds_tpu.utils import sigs
from dds_tpu.utils.trace import tracer
from dds_tpu.utils.trust import TrustedNodesList

log = logging.getLogger("dds.replica")


@dataclass
class ReplicaConfig:
    quorum_size: int = 5
    nonce_increment: int = 1
    abd_mac_secret: bytes = b"intranet-abd-secret"
    proxy_mac_secret: bytes = b"rest2abd"
    debug: bool = False
    # honor the Crash/Compromise fault-injection backdoors. True is the
    # harness default (tests drive faults directly); deployments built by
    # run.launch() set it from `attacks.enabled`, so a production config
    # without attack simulation ignores injected faults entirely — one
    # credentialed peer must not be able to kill replicas past f.
    allow_fault_injection: bool = True


@dataclass
class _Outgoing:
    client: str
    call: object
    client_nonce: int
    expired: bool = False
    # sender -> (tag, value, signature). The reference accumulates a set of
    # reply *tuples* (`OutgoingRequestState.scala:14`), which counts
    # duplicate replies from one replica as distinct quorum votes (JVM
    # byte-array identity equality) — a replay could forge a quorum. We key
    # by sender, like its write quorum already does.
    read_quorum: dict = field(default_factory=dict)
    write_quorum: set = field(default_factory=set)
    set_to_read: object = None
    set_to_write: object = None
    tag_to_reply: object = None  # tag returned to the proxy (read max / written)


class BFTABDNode:
    """One replica endpoint. `addr` must appear in `replicas`."""

    def __init__(
        self,
        addr: str,
        replicas: list[str],
        supervisor: str,
        net: Transport,
        config: ReplicaConfig | None = None,
        shard=None,
    ):
        self.addr = addr
        self.name = addr.rsplit("/", 1)[-1]
        self.all_replicas = list(replicas)
        self.supervisor = supervisor
        self.net = net
        self.cfg = config or ReplicaConfig()
        self.behavior = "healthy"
        # monotonic floor for tags this coordinator mints: two concurrent
        # writes coordinated here could otherwise read the same quorum-max
        # and mint the SAME (seq+1, self) tag for different values (latent
        # in the reference too, `BFTABDNode.scala:194`); the floor keeps
        # locally-minted tags unique. Deviation documented per SURVEY.md §7.
        self._seq_floor = 0
        self.repository: dict[str, tuple[M.ABDTag, object]] = {}
        self.outgoing: dict[int, _Outgoing] = {}
        self.incoming: dict[int, bool] = {}  # nonce -> expired
        self.siblings = TrustedNodesList(replicas)
        # bumped on every observable repository change (stored Write, Sleep
        # reseed, Kill wipe, snapshot restore); versions the tag-batch cache
        self.repo_version = 0
        # keys-tuple -> (repo_version, digest, tags, fingerprint): memoizes
        # the per-key-set tag vector + its MAC inputs between repository
        # changes, making repeat ReadTagBatch rounds O(1) instead of O(K)
        self._tagbatch_cache: dict[tuple, tuple] = {}
        # Aegis: incremental (key -> tag, value-digest) hash index — the
        # source of StateDigest manifests and the anti-entropy tree
        self.merkle = MerkleIndex()
        # per-replica sync agent; run.launch (or a test) starts its loop
        self.antientropy = AntiEntropy(self)
        # verified-reseed sessions in flight: session -> {begin, chunks}
        # (SleepBegin and StateChunks may arrive in any order)
        self._recovery_sessions: dict[int, dict] = {}
        # Constellation: the group's shared fencing state (shard.ShardState
        # duck-type: group_id / epoch / owns(key)). None = unsharded, no
        # fencing. Shard-migration sessions buffer separately from
        # recovery reseeds — completing one must never replace the
        # repository or flip behavior.
        self.shard = shard
        self._migrate_sessions: dict[int, dict] = {}
        # last snapshot save/load bookkeeping (core/snapshot fills it;
        # exported via /health + scrape-time gauges)
        self.snapshot_meta: dict = {}
        # Atlas read-lease geometry: the group's shared geo.LeaseTable
        # (None = leases off). While any lease is active, every quorum
        # this coordinator closes must include the holders — that is the
        # whole safety argument for region-local reads (dds_tpu/geo).
        self.lease_table = None
        net.register(addr, self.handle)

    # ------------------------------------------------------------------ util

    def _state(self, key: str) -> tuple[M.ABDTag, object]:
        if key not in self.repository:
            self.repository[key] = (M.ABDTag(0, self.name), None)
        return self.repository[key]

    def _send(self, dest: str, msg) -> None:
        self.net.send(self.addr, dest, msg)

    def _suspect(self, endpoint: str) -> None:
        tracer.event("replica.suspect", by=self.name, suspect=endpoint)
        metrics.inc(
            "dds_suspect_votes_total", suspect=endpoint.rsplit("/", 1)[-1],
            help="Suspect votes raised toward the supervisor",
        )
        self._send(self.supervisor, M.Suspect(endpoint, sigs.generate_nonce()))

    def _debug(self, text: str) -> None:
        if self.cfg.debug:
            log.info("%s: %s", self.name, text)

    def _broadcast(self, msg) -> None:
        for sibling in self.siblings.get_trusted():
            self._send(sibling, msg)

    def _store(self, key: str, tag: M.ABDTag, value) -> None:
        """The ONLY place stored tags change: bump the version so cached
        tag-batch vectors (and their fingerprints) invalidate."""
        self.repository[key] = (tag, value)
        self.repo_version += 1
        self.merkle.update(key, tag, value)

    def _install_repository(self, repository: dict) -> None:
        """Replace the whole repository (reseed / snapshot restore): bump
        the version, drop memo caches, rebuild the Merkle index."""
        self.repository = repository
        self.repo_version += 1
        self._tagbatch_cache.clear()
        self.merkle.rebuild(repository)

    def _wipe(self) -> None:
        self.repository = {}
        self.outgoing = {}
        self.incoming = {}
        self.repo_version += 1
        self._tagbatch_cache.clear()
        self.merkle.rebuild({})
        self._recovery_sessions.clear()

    def _quorum_met(self, responders) -> bool:
        """Quorum gate for the rounds this coordinator closes. Plain
        `>= quorum_size` — except while read leases are out, when the
        quorum must ALSO contain every active holder: a leased replica
        then stores each acked write (and each fast-path-readable value)
        before the round completes, so its local reads can never trail an
        acked cross-region write. A dead holder stalls rounds at most one
        lease TTL (expiry drops it from `holders()`)."""
        if len(responders) < self.cfg.quorum_size:
            return False
        if self.lease_table is None:
            return True
        holders = self.lease_table.holders()
        if not holders:
            return True
        names = {s.rsplit("/", 1)[-1] for s in responders}
        return holders <= names

    def _shard_fenced(self, key: str) -> bool:
        """True when this group must NOT serve `key` under its current
        shard map (Constellation epoch fencing). Unsharded nodes never
        fence."""
        return self.shard is not None and not self.shard.owns(key)

    def _reply_wrong_shard(self, dest: str, key: str, nonce: int,
                           sent_epoch: int, what: str) -> None:
        """Typed, signed fence rejection: tells the proxy its map is
        stale (or a reshard is in flight) so it refreshes and re-routes
        under its existing Deadline budget — the no-silent-misroutes leg
        of a live reshard."""
        epoch = self.shard.epoch
        sig = sigs.proxy_signature(
            self.cfg.proxy_mac_secret, key, nonce, ["wrong-shard", epoch]
        )
        metrics.inc(
            "dds_shard_fenced_total", shard=str(self.shard.group_id),
            msg=what,
            help="requests fenced for keys outside the group's shard map",
        )
        tracer.event("shard.fence", replica=self.name, key=key,
                     epoch=epoch, sent_epoch=sent_epoch, msg=what)
        self._send(dest, M.WrongShard(key, epoch, nonce, sig))

    def _tag_batch_fill(self, keys: tuple, digest: str) -> tuple[tuple, bytes]:
        """(tag vector, fingerprint) for an AUTHENTICATED ReadTagBatch,
        memoized per keys-tuple until the repository changes. Aggregates
        revalidate the same key set every round; between writes this makes
        the replica side O(1) instead of O(K). The digest stored with a hit
        was computed from these exact keys when the entry was filled (the
        tuple is the cache key), so it still authenticates them on probe."""
        # read without materializing default entries in the repository
        blank = (M.ABDTag(0, self.name), None)
        tags = tuple(self.repository.get(k, blank)[0] for k in keys)
        fp = sigs.tags_fingerprint(tags)
        if len(self._tagbatch_cache) > 8:  # distinct key-sets stay bounded
            self._tagbatch_cache.clear()
        self._tagbatch_cache[keys] = (self.repo_version, digest, tags, fp)
        return tags, fp

    # ------------------------------------------------------------- dispatch

    async def handle(self, sender: str, msg) -> None:
        # Per-replica span: the message arrived in a task whose contextvars
        # were copied at send time (InMemoryNet) or restored from the
        # frame's `tc` field (TcpNet), so this span slots into the
        # originating request's trace tree — the per-replica attribution a
        # process-global ring could never give. `replica` meta identifies
        # WHICH replica served each quorum leg.
        meta = {
            "replica": self.name, "msg": type(msg).__name__,
            "behavior": self.behavior,
        }
        # per-key attribution where the protocol message names one: lets
        # the Watchtower auditor (and a human reading an incident) tie a
        # phase participant to the record it touched
        key = getattr(msg, "key", None)
        if isinstance(key, str):
            meta["key"] = key
        with tracer.span("replica.handle", **meta):
            await self._dispatch(sender, msg)

    async def _dispatch(self, sender: str, msg) -> None:
        if isinstance(msg, (M.Crash, M.Compromise)):
            # fault-injection backdoors (Trudy): honored only when the
            # deployment enables attack simulation
            if not self.cfg.allow_fault_injection:
                self._debug(f"ignoring injected {type(msg).__name__}")
                return
            if isinstance(msg, M.Crash):
                self.net.unregister(self.addr)  # go silent, any behavior
                return
        if self.behavior == "healthy":
            await self._healthy(sender, msg)
        elif self.behavior == "sentinent":
            await self._sentinent(sender, msg)
        else:
            await self._byzantine(sender, msg)

    # -------------------------------------------------------------- healthy

    async def _healthy(self, sender: str, msg) -> None:
        cfg = self.cfg
        match msg:
            case M.Envelope(call, nonce, signature):
                if nonce in self.outgoing:
                    self._debug("invalid nonce from proxy - repeated")
                    return
                req = _Outgoing(sender, call, nonce)
                match call:
                    case M.IRead(key):
                        if not sigs.validate_proxy_signature(
                            cfg.proxy_mac_secret, key, nonce, signature
                        ):
                            self._debug("invalid proxy signature")
                        elif self._shard_fenced(key):
                            # fence AFTER authentication (an unauthenticated
                            # probe must not learn the keyspace layout) and
                            # burn the request so a replay cannot re-ask
                            req.expired = True
                            self._reply_wrong_shard(
                                sender, key, nonce + cfg.nonce_increment,
                                msg.epoch, "IRead",
                            )
                        else:
                            self._broadcast(M.Read(key, nonce))
                    case M.IWrite(key, value):
                        if not sigs.validate_proxy_signature(
                            cfg.proxy_mac_secret, key, nonce, signature, value
                        ):
                            self._debug("invalid proxy signature")
                        elif self._shard_fenced(key):
                            req.expired = True
                            self._reply_wrong_shard(
                                sender, key, nonce + cfg.nonce_increment,
                                msg.epoch, "IWrite",
                            )
                        else:
                            req.set_to_write = value
                            self._broadcast(M.ReadTag(key, nonce))
                    case _:
                        log.error("unexpected API call from proxy: %r", call)
                self.outgoing[nonce] = req

            case M.ReadTag(key, nonce):
                if nonce in self.incoming:
                    self._debug("invalid nonce - repeated")
                    self._suspect(sender)
                    return
                self.incoming[nonce] = False
                tag, contents = self._state(key)
                sig = sigs.abd_signature(cfg.abd_mac_secret, contents, tag, nonce)
                self._send(sender, M.TagReply(tag, key, contents, sig, nonce))

            case M.ReadTagBatch(keys, nonce, psig, pfp):
                # sent straight by the proxy (AbdClient.read_tags), not by a
                # coordinator: authenticate the request BEFORE burning an
                # anti-replay nonce, or unauthenticated traffic could both
                # enumerate tags (write-activity oracle) and grow the nonce
                # set without bound. The memo cache is PROBED read-only here
                # (a hit skips the O(K) digest recompute) but only FILLED
                # after the MAC verifies — pre-auth traffic must not be able
                # to evict the hot entry or grow the cache
                hit = self._tagbatch_cache.get(keys)
                if hit is not None and hit[0] == self.repo_version:
                    digest = hit[1]
                else:
                    hit = None
                    digest = sigs.key_from_set(list(keys))
                if not sigs.validate_proxy_signature(
                    cfg.proxy_mac_secret, digest, nonce, psig
                ):
                    self._debug("invalid proxy signature (tag batch)")
                    return
                if nonce in self.incoming:
                    self._debug("invalid nonce - repeated (tag batch)")
                    self._suspect(sender)
                    return
                if self.shard is not None:
                    bad = next(
                        (k for k in keys if self._shard_fenced(k)), None
                    )
                    if bad is not None:
                        # batch replies correlate by the REQUEST nonce
                        self.incoming[nonce] = True
                        self._reply_wrong_shard(
                            sender, bad, nonce, msg.epoch, "ReadTagBatch"
                        )
                        return
                if hit is not None:
                    tags, fp = hit[2], hit[3]
                else:
                    tags, fp = self._tag_batch_fill(keys, digest)
                # tag-only phase: no Write follows, so the nonce is spent now
                self.incoming[nonce] = True
                if pfp is not None and pfp == fp:
                    # steady-state fast path: assert vector equality by
                    # fingerprint instead of shipping/MACing all K tags
                    sig = sigs.abd_batch_unchanged_signature(
                        cfg.abd_mac_secret, fp, digest, nonce
                    )
                    self._send(
                        sender,
                        M.TagBatchReply((), digest, sig, nonce,
                                        unchanged=True, fingerprint=fp),
                    )
                else:
                    sig = sigs.abd_batch_signature(
                        cfg.abd_mac_secret, tags, digest, nonce
                    )
                    self._send(
                        sender,
                        M.TagBatchReply(tags, digest, sig, nonce, fingerprint=fp),
                    )

            case M.TagReply(tag, key, value, signature, nonce):
                if not sigs.validate_abd_signature(
                    cfg.abd_mac_secret, value, tag, nonce, signature
                ):
                    self._debug("invalid ABD signature")
                    self._suspect(sender)
                    return
                req = self.outgoing.get(nonce)
                if req is None:
                    self._debug("invalid nonce - unknown")
                    self._suspect(sender)
                    return
                if req.expired:
                    self._debug("invalid nonce - expired (late quorum reply)")
                    return
                if not isinstance(req.call, M.IWrite):
                    # a reply type must match its request's phase: a forged
                    # TagReply against a read/tag-read nonce would otherwise
                    # pollute that quorum accumulator
                    self._debug("TagReply for a non-write request")
                    self._suspect(sender)
                    return
                req.read_quorum[sender] = (tag, value, signature)
                if len(req.read_quorum) >= cfg.quorum_size:
                    max_tag = max(t for t, _, _ in req.read_quorum.values())
                    req.read_quorum = {}
                    self._seq_floor = max(self._seq_floor, max_tag.seq) + 1
                    new_tag = M.ABDTag(self._seq_floor, self.name)
                    req.tag_to_reply = new_tag
                    sig = sigs.abd_signature(
                        cfg.abd_mac_secret, req.set_to_write, new_tag, nonce
                    )
                    self._broadcast(M.Write(new_tag, key, req.set_to_write, sig, nonce))

            case M.Write(tag, key, value, signature, nonce):
                if not sigs.validate_abd_signature(
                    cfg.abd_mac_secret, value, tag, nonce, signature
                ):
                    self._debug("invalid ABD signature")
                    self._suspect(sender)
                    return
                if nonce not in self.incoming:
                    self._debug("invalid nonce - unknown")
                    self._suspect(sender)
                    return
                if self.incoming[nonce]:
                    self._debug("invalid nonce - expired at Write (late quorum reply)")
                    return
                self.incoming[nonce] = True
                if self._shard_fenced(key):
                    # storage-layer fence: a Write minted under a stale
                    # epoch (coordinator raced the map install) is neither
                    # stored nor acked — the op can't reach quorum, the
                    # client retries, and the retry fences at the
                    # coordinator. Zero stale-epoch writes ever land.
                    metrics.inc(
                        "dds_shard_fenced_total",
                        shard=str(self.shard.group_id), msg="Write",
                        help="requests fenced for keys outside the group's "
                             "shard map",
                    )
                    tracer.event("shard.fence", replica=self.name, key=key,
                                 epoch=self.shard.epoch, msg="Write")
                    return
                cur_tag, _ = self._state(key)
                if cur_tag < tag:
                    self._store(key, tag, value)
                self._send(sender, M.WriteAck(key, nonce))

            case M.WriteAck(key, nonce):
                req = self.outgoing.get(nonce)
                if req is None:
                    self._debug("invalid nonce - unknown")
                    self._suspect(sender)
                    return
                if req.expired:
                    self._debug("invalid nonce - expired at WriteAck (late reply)")
                    return
                if not isinstance(req.call, (M.IRead, M.IWrite)):
                    self._debug("WriteAck for a request with no write phase")
                    self._suspect(sender)
                    return
                req.write_quorum.add(sender)
                if self._quorum_met(req.write_quorum):
                    req.write_quorum = set()
                    req.expired = True
                    challenge = req.client_nonce + cfg.nonce_increment
                    match req.call:
                        case M.IRead(k):
                            # the MAC covers the tag too: tags are
                            # predictable, so an unsigned tag could be
                            # swapped in transit to poison tag-validated
                            # caching at the proxy
                            sig = sigs.proxy_signature(
                                cfg.proxy_mac_secret,
                                k,
                                challenge,
                                [req.set_to_read, sigs.tag_payload(req.tag_to_reply)],
                            )
                            self._send(
                                req.client,
                                M.Envelope(
                                    M.IReadReply(
                                        k, req.set_to_read, tag=req.tag_to_reply
                                    ),
                                    challenge,
                                    sig,
                                ),
                            )
                        case M.IWrite(k, _):
                            sig = sigs.proxy_signature(
                                cfg.proxy_mac_secret,
                                k,
                                challenge,
                                sigs.tag_payload(req.tag_to_reply),
                            )
                            self._send(
                                req.client,
                                M.Envelope(
                                    M.IWriteReply(k, tag=req.tag_to_reply),
                                    challenge,
                                    sig,
                                ),
                            )

            case M.Read(key, nonce):
                if nonce in self.incoming:
                    self._debug("invalid nonce - repeated")
                    self._suspect(sender)
                    return
                self.incoming[nonce] = False
                tag, contents = self._state(key)
                sig = sigs.abd_signature(cfg.abd_mac_secret, contents, tag, nonce)
                self._send(sender, M.ReadReply(tag, key, contents, sig, nonce))

            case M.ReadReply(tag, key, value, signature, nonce):
                if not sigs.validate_abd_signature(
                    cfg.abd_mac_secret, value, tag, nonce, signature
                ):
                    self._debug("invalid ABD signature")
                    self._suspect(sender)
                    return
                req = self.outgoing.get(nonce)
                if req is None:
                    self._debug("invalid nonce - unknown")
                    self._suspect(sender)
                    return
                if req.expired:
                    self._debug("invalid nonce - expired at ReadReply (late reply)")
                    return
                if not isinstance(req.call, M.IRead):
                    self._debug("ReadReply for a non-read request")
                    self._suspect(sender)
                    return
                req.read_quorum[sender] = (tag, value, signature)
                if self._quorum_met(req.read_quorum):
                    entries = list(req.read_quorum.values())
                    max_tag, max_val, max_sig = max(entries, key=lambda e: e[0])
                    req.read_quorum = {}
                    req.set_to_read = max_val
                    req.tag_to_reply = max_tag
                    if all(t == max_tag for t, _, _ in entries):
                        # Standard ABD read optimization (deviation from the
                        # reference, which always writes back): every quorum
                        # member already reported (max_tag, value), so the
                        # value IS stored at a full quorum and the write-back
                        # phase adds nothing — any later read's quorum
                        # intersects this one. Answer the proxy directly.
                        # (A Byzantine member forging an equal tag with a
                        # different value needs the intranet MAC secret —
                        # with which it could equally poison the write-back
                        # path, so the threat model is unchanged.)
                        req.expired = True
                        challenge = req.client_nonce + cfg.nonce_increment
                        k = req.call.key
                        sig = sigs.proxy_signature(
                            cfg.proxy_mac_secret,
                            k,
                            challenge,
                            [max_val, sigs.tag_payload(max_tag)],
                        )
                        self._send(
                            req.client,
                            M.Envelope(
                                M.IReadReply(k, max_val, tag=max_tag),
                                challenge,
                                sig,
                            ),
                        )
                        return
                    # ABD write-back phase, re-using the original signature
                    self._broadcast(M.Write(max_tag, key, max_val, max_sig, nonce))

            case M.LeaseRequest(region, ttl, nonce, signature):
                if not sigs.validate_manifest_signature(
                    cfg.abd_mac_secret, "lease-request",
                    {"region": region, "ttl": ttl}, nonce, signature,
                ):
                    self._debug("invalid lease-request signature")
                    return
                if nonce in self.incoming:
                    self._debug("invalid nonce - repeated (lease request)")
                    self._suspect(sender)
                    return
                self.incoming[nonce] = True
                ok = self.lease_table is not None
                token, expires = "", 0.0
                if ok:
                    lease = self.lease_table.grant(region, self.name,
                                                   float(ttl))
                    token, expires = lease.token, lease.expires
                    tracer.event("geo.lease_grant", replica=self.name,
                                 region=region, ttl=float(ttl))
                rsig = sigs.manifest_signature(
                    cfg.abd_mac_secret, "lease-grant",
                    {"region": region, "replica": self.name, "token": token,
                     "expires": expires, "ok": ok}, nonce,
                )
                self._send(sender, M.LeaseGrant(region, self.name, token,
                                                expires, ok, nonce, rsig))

            case M.LeaseRevoke(region, nonce, signature):
                if not sigs.validate_manifest_signature(
                    cfg.abd_mac_secret, "lease-revoke",
                    {"region": region}, nonce, signature,
                ):
                    self._debug("invalid lease-revoke signature")
                    return
                if nonce in self.incoming:
                    self._debug("invalid nonce - repeated (lease revoke)")
                    self._suspect(sender)
                    return
                self.incoming[nonce] = True
                if self.lease_table is not None:
                    self.lease_table.revoke(region)
                    tracer.event("geo.lease_revoke", replica=self.name,
                                 region=region)

            case M.LocalRead(key, region, token, nonce, signature):
                if not sigs.validate_proxy_signature(
                    cfg.proxy_mac_secret, key, nonce, signature,
                    ["local-read", region],
                ):
                    self._debug("invalid proxy signature (local read)")
                    return
                if nonce in self.incoming:
                    self._debug("invalid nonce - repeated (local read)")
                    self._suspect(sender)
                    return
                self.incoming[nonce] = True
                served = (
                    self.lease_table is not None
                    and self.lease_table.valid(region, self.name, token)
                    and not self._shard_fenced(key)
                )
                if served:
                    tag, value = self._state(key)
                else:
                    # typed refusal (bad/expired/revoked lease, or a fence):
                    # the proxy falls back to a full quorum read NOW instead
                    # of timing out a WAN round-trip first
                    tag, value = None, None
                metrics.inc(
                    "dds_geo_local_reads_total",
                    result="served" if served else "refused",
                    replica=self.name,
                    help="lease-backed region-local reads by outcome",
                )
                rsig = sigs.proxy_signature(
                    cfg.proxy_mac_secret, key, nonce,
                    [served, value,
                     sigs.tag_payload(tag) if tag is not None else None],
                )
                self._send(sender, M.LocalReadReply(tag, key, value, served,
                                                    nonce, rsig))

            case M.Sleep(data, nonces):
                # legacy unverified reseed (kept for deployments that turn
                # verified_transfer off): the seeding state is trusted
                # verbatim — the blind spot the SleepBegin path closes
                self._install_repository({
                    k: (M.ABDTag(v["tag"][0], v["tag"][1]), v["value"])
                    for k, v in data.items()
                })
                for n in nonces:
                    self.incoming[int(n)] = True
                self._debug("going to sleep")
                self._send(sender, M.Complying())
                self.behavior = "sentinent"

            case M.SleepBegin():
                self._recovery_ingest(sender, msg)

            case M.ShardMigrateBegin():
                self._migrate_ingest(sender, msg)

            case M.StateChunk():
                if msg.kind == "migrate":
                    self._migrate_ingest(sender, msg)
                else:
                    self._recovery_ingest(sender, msg)

            case M.StateDigestRequest(nonce):
                manifest = self.merkle.manifest()
                sig = sigs.manifest_signature(
                    cfg.abd_mac_secret, self.addr, manifest, nonce
                )
                self._send(sender, M.StateDigest(manifest, nonce, sig))

            case (M.MerkleRootRequest() | M.MerkleBucketRequest()
                  | M.MerkleKeysRequest() | M.RepairRequest() | M.MerkleRoot()
                  | M.MerkleBuckets() | M.MerkleKeys() | M.RepairReply()):
                self.antientropy.handle(sender, msg)

            case M.Kill():
                # guardian-restart semantics: fresh empty state, healthy
                self._wipe()
                self.behavior = "healthy"
                self._debug("killed and restarted")

            case M.Compromise():
                self.behavior = "byzantine"

            case _:
                self._debug(f"unhandled {type(msg).__name__}")

    # ------------------------------------------------------------ sentinent

    async def _sentinent(self, sender: str, msg) -> None:
        cfg = self.cfg
        match msg:
            case M.Write(tag, key, value, signature, nonce):
                if not sigs.validate_abd_signature(
                    cfg.abd_mac_secret, value, tag, nonce, signature
                ):
                    self._debug("invalid ABD signature (sentinent)")
                    return
                if nonce in self.incoming:
                    self._debug("invalid nonce - repeated (sentinent)")
                    return
                self.incoming[nonce] = True
                if self._shard_fenced(key):
                    return  # same storage fence as the healthy path
                cur_tag, _ = self._state(key)
                if cur_tag < tag:
                    self._store(key, tag, value)

            case M.Awake():
                self._debug("waking up")
                data = {
                    k: {"tag": [t.seq, t.id], "value": v}
                    for k, (t, v) in self.repository.items()
                }
                self._send(sender, M.State(data, list(self.incoming.keys())))
                self.behavior = "healthy"

            case M.StateDigestRequest(nonce):
                # the supervisor's spare-freshness probe and the verified-
                # transfer quorum both reach spares too
                manifest = self.merkle.manifest()
                sig = sigs.manifest_signature(
                    cfg.abd_mac_secret, self.addr, manifest, nonce
                )
                self._send(sender, M.StateDigest(manifest, nonce, sig))

            case (M.MerkleRootRequest() | M.MerkleBucketRequest()
                  | M.MerkleKeysRequest() | M.RepairRequest() | M.MerkleRoot()
                  | M.MerkleBuckets() | M.MerkleKeys() | M.RepairReply()):
                # spares sync too: a snapshot-restored sentinent converges
                # before it is ever promoted
                self.antientropy.handle(sender, msg)

            case M.ShardMigrateBegin():
                # spares of a NEW group ingest the migration too, so a
                # later promotion starts warm instead of divergent
                self._migrate_ingest(sender, msg)

            case M.StateChunk() if msg.kind == "migrate":
                self._migrate_ingest(sender, msg)

            case M.Kill():
                self._wipe()
                self.behavior = "healthy"

    # ------------------------------------------------------------ byzantine

    async def _byzantine(self, sender: str, msg) -> None:
        """Simulated compromise, mirroring `BFTABDNode.scala:420-469`:
        garbage replies, replays, forged writes, omissions — and note the
        attacker DOES hold the real MAC key (kept per the reference threat
        model, SURVEY.md §7)."""
        cfg = self.cfg
        match msg:
            case M.Envelope(_, _, _):
                # protocol violation: bare reply, not an Envelope
                self._send(sender, M.IReadReply("2eikd094akldslcnu94342", None))

            case M.ReadTag(key, nonce):
                garbage = [1, "i am ", "trudy", None]
                for _ in range(4):  # replay x4 with empty signature
                    self._send(
                        sender,
                        M.TagReply(M.ABDTag(0, self.name), key, garbage, b"", nonce),
                    )

            case M.ReadTagBatch(keys, nonce, _):
                # inflated tags under an empty signature, replayed x2: the
                # proxy drops these on MAC failure; even if the tags landed
                # they could only force spurious cache re-fetches
                fake = tuple(M.ABDTag(1 << 30, self.name) for _ in keys)
                for _ in range(2):
                    self._send(sender, M.TagBatchReply(fake, "forged", b"", nonce))

            case M.TagReply(_, key, _, _, nonce) | M.ReadReply(_, key, _, _, nonce):
                # forge a write to every replica under a random tag
                tag = M.ABDTag(random.getrandbits(31), sender.rsplit("/", 1)[-1])
                sig = sigs.abd_signature(cfg.abd_mac_secret, None, tag, nonce + 1)
                for replica in self.all_replicas:
                    self._send(replica, M.Write(tag, key, None, sig, nonce + 1))

            case M.Write(_, key, _, _, nonce):
                self._send(sender, M.WriteAck(key, nonce))

            case M.WriteAck(_, _):
                pass  # omission

            case M.Read(key, nonce):
                tag = M.ABDTag(random.getrandbits(31), sender.rsplit("/", 1)[-1])
                self._send(
                    sender,
                    M.ReadReply(tag, key, [",test,", 31, True], b"10010100110010", nonce),
                )

            case M.Kill():
                self._wipe()
                self.behavior = "healthy"

    # ------------------------------------------------- verified state seed

    MAX_RECOVERY_SESSIONS = 4

    def _recovery_ingest(self, sender: str, msg) -> None:
        """Buffer one frame of a verified reseed (SleepBegin header or a
        StateChunk); transports reorder, so completion is by count, not
        order. Sessions are bounded: a flood of bogus session ids evicts
        oldest-first instead of growing without bound."""
        sess = self._recovery_sessions.get(msg.session)
        if sess is None:
            while len(self._recovery_sessions) >= self.MAX_RECOVERY_SESSIONS:
                self._recovery_sessions.pop(next(iter(self._recovery_sessions)))
            sess = self._recovery_sessions[msg.session] = {
                "begin": None, "sender": None, "chunks": {},
            }
        if isinstance(msg, M.SleepBegin):
            sess["begin"] = msg
            sess["sender"] = sender
        else:
            sess["chunks"][int(msg.seq)] = msg.entries
        self._try_complete_recovery(msg.session)

    def _try_complete_recovery(self, session: int) -> None:
        sess = self._recovery_sessions.get(session)
        begin = sess["begin"]
        if begin is None:
            return
        chunks = sess["chunks"]
        if sum(1 for s in chunks if 0 <= s < begin.total) < begin.total:
            return
        verified = self._verified_manifest(begin.digests, begin.support)
        repository: dict[str, tuple] = {}
        rejected: list[str] = []
        for seq in range(begin.total):
            for key, e in chunks[seq].items():
                try:
                    tag = M.ABDTag(int(e["tag"][0]), str(e["tag"][1]))
                    value = e["value"]
                except (KeyError, TypeError, ValueError, IndexError):
                    rejected.append(key)
                    continue
                want = verified.get(key)
                if want == (tag.seq, tag.id, sigs.value_digest(value)):
                    repository[key] = (tag, value)
                else:
                    rejected.append(key)
        self._recovery_sessions.pop(session, None)
        self._install_repository(repository)
        for n in begin.nonces:
            self.incoming[int(n)] = True
        if rejected:
            log.warning(
                "%s: verified reseed rejected %d/%d entries (digest quorum "
                "mismatch) — anti-entropy will repair the holes",
                self.name, len(rejected), len(rejected) + len(repository),
            )
            tracer.event("recovery.rejected_entries", replica=self.name,
                         rejected=len(rejected), accepted=len(repository))
            metrics.inc(
                "dds_recovery_rejected_entries_total", len(rejected),
                replica=self.name,
                help="seeded entries rejected by the digest quorum",
            )
            flight.record(
                "recovery_digest_mismatch", replica=self.name,
                rejected=sorted(rejected)[:32], accepted=len(repository),
            )
        self._debug(
            f"reseeded with {len(repository)} verified entries "
            f"({len(rejected)} rejected); going to sleep"
        )
        self._send(sess["sender"], M.Complying())
        self.behavior = "sentinent"

    def _verified_manifest(self, digests: list, support: int) -> dict:
        return verified_manifest(digests, support, self.cfg.abd_mac_secret)

    # -------------------------------------------------- shard migration

    MAX_MIGRATE_SESSIONS = 4

    def _migrate_ingest(self, sender: str, msg) -> None:
        """Buffer one frame of a Constellation key migration (header or a
        kind="migrate" StateChunk). Same reorder-tolerant, bounded session
        buffering as recovery — but completion MERGES, never replaces."""
        sess = self._migrate_sessions.get(msg.session)
        if sess is None:
            while len(self._migrate_sessions) >= self.MAX_MIGRATE_SESSIONS:
                self._migrate_sessions.pop(next(iter(self._migrate_sessions)))
            sess = self._migrate_sessions[msg.session] = {
                "begin": None, "sender": None, "chunks": {},
            }
        if isinstance(msg, M.ShardMigrateBegin):
            sess["begin"] = msg
            sess["sender"] = sender
        else:
            sess["chunks"][int(msg.seq)] = msg.entries
        self._try_complete_migration(msg.session)

    def _try_complete_migration(self, session: int) -> None:
        sess = self._migrate_sessions.get(session)
        begin = sess["begin"]
        if begin is None:
            return
        chunks = sess["chunks"]
        if sum(1 for s in chunks if 0 <= s < begin.total) < begin.total:
            return
        verified = self._verified_manifest(begin.digests, begin.support)
        accepted = rejected = 0
        for seq in range(begin.total):
            for key, e in chunks[seq].items():
                try:
                    tag = M.ABDTag(int(e["tag"][0]), str(e["tag"][1]))
                    value = e["value"]
                except (KeyError, TypeError, ValueError, IndexError):
                    rejected += 1
                    continue
                # the receiving group only takes keys its OWN map assigns
                # it — a Byzantine rebalancer cannot use a migration to
                # park foreign keys on this group
                if self.shard is not None and not self.shard.owns(key):
                    rejected += 1
                    continue
                want = verified.get(key)
                if want != (tag.seq, tag.id, sigs.value_digest(value)):
                    rejected += 1
                    continue
                cur_tag = self.repository.get(key, (M.ABDTag(0, self.name),
                                                    None))[0]
                if cur_tag < tag:
                    self._store(key, tag, value)
                accepted += 1  # installed, or already at/above the attested tag
        self._migrate_sessions.pop(session, None)
        metrics.inc(
            "dds_shard_migrated_keys_total", accepted, replica=self.name,
            help="verified keys accepted during shard migrations",
        )
        if rejected:
            tracer.event("shard.migrate_rejected", replica=self.name,
                         rejected=rejected, accepted=accepted)
            flight.record(
                "shard_migrate_rejected", replica=self.name,
                rejected=rejected, accepted=accepted, session=session,
            )
        self._debug(
            f"shard migration {session}: {accepted} accepted, "
            f"{rejected} rejected"
        )
        self._send(sess["sender"], M.ShardMigrateAck(session, accepted,
                                                     rejected))

    def drop_unowned(self) -> int:
        """Prune repository entries outside this group's shard map (after
        a migration activates). Returns the number of keys dropped."""
        if self.shard is None:
            return 0
        doomed = [k for k in self.repository if not self.shard.owns(k)]
        for k in doomed:
            del self.repository[k]
        if doomed:
            self.repo_version += 1
            self._tagbatch_cache.clear()
            self.merkle.rebuild(self.repository)
        return len(doomed)

    # ---------------------------------------------------------------- admin

    def export_state(self) -> dict:
        return {
            k: {"tag": [t.seq, t.id], "value": v} for k, (t, v) in self.repository.items()
        }


def verified_manifest(digests: list, support: int, secret: bytes) -> dict:
    """Cross-check a relayed manifest quorum: verify every HMAC (the
    signer address is bound into it, so a relay cannot re-attribute)
    and keep only entries attested identically by >= `support` (= f+1)
    distinct signers — at least one of which is then honest, so no
    single Byzantine spare or relay can smuggle a forged entry. Shared
    by verified recovery reseeds, shard-migration ingest, and the
    rebalancer's source-side planning (shard/rebalance)."""
    votes: dict[tuple, set] = {}
    for item in digests:
        try:
            signer, manifest, nonce, sighex = item
            if not sigs.validate_manifest_signature(
                secret, str(signer), manifest,
                int(nonce), bytes.fromhex(sighex),
            ):
                continue
        except (TypeError, ValueError):
            continue
        for key, ent in manifest.items():
            try:
                attested = (str(key), int(ent[0]), str(ent[1]), str(ent[2]))
            except (TypeError, ValueError, IndexError):
                continue
            votes.setdefault(attested, set()).add(str(signer))
    verified: dict[str, tuple] = {}
    for (key, seq, tid, vd), signers in votes.items():
        if len(signers) < support:
            continue
        cur = verified.get(key)
        if cur is None or (seq, tid) > (cur[0], cur[1]):
            verified[key] = (seq, tid, vd)
    return verified
