"""Membership + failure manager: suspicion quorums, spares, proactive recovery.

Counterpart of `dds/core/BFTSupervisor.scala`: tracks active/sentinent
replica lists, dedupes `Suspect` votes by nonce, recovers a replica once a
quorum of distinct voters suspects it, proactively recovers the oldest
active replica on a timer, and serves proxies the freshest half of the
active list.

Recovery (BFTSupervisor.scala:97-153): wake a random sentinent spare
(`Awake` -> `State{data, nonces}`), promote it to active; `Kill` the
offender (guardian-restart semantics) and re-seed it with the spare's state
via `Sleep` -> `Complying`, demoting it to sentinent. If the offender's
host is dead (ask timeout), redeploy a fresh replica at the same endpoint
through the injected factory and seed that instead. Nodes that prove
unreachable — a spare that never Awakes, or an offender that never
Complies after redeploy — accrue strikes; one miss is treated as transient
(slow restart, supervisor-side blip) and the node stays a (deprioritized)
spare, but DROP_STRIKES consecutive failures drop it from membership with
a loud warning rather than keeping a phantom that pins future recoveries
(deviation from the reference, which would retry forever); the operator
restores dropped nodes explicitly. Successful contact clears strikes.

Deviations (documented): suspicion voters are the *senders* of Suspect
votes (the reference seeds the voter set with the suspected node itself,
`BFTSupervisor.scala:79` — a bookkeeping bug); `RequestReplicas` returns at
least one endpoint even with a single active replica.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from dataclasses import dataclass
from typing import Awaitable, Callable, Optional

from dds_tpu.core import messages as M
from dds_tpu.core.transport import Transport
from dds_tpu.obs.flight import flight
from dds_tpu.obs.metrics import metrics
from dds_tpu.utils import sigs
from dds_tpu.utils.tasks import supervised_task
from dds_tpu.utils.trace import tracer

log = logging.getLogger("dds.supervisor")


@dataclass
class SupervisorConfig:
    quorum_size: int = 5
    proactive_recovery_warmup: float = 5.0
    proactive_recovery_interval: float = 7.0
    sentinent_awake_timeout: float = 5.0
    crashed_recovery_timeout: float = 12.0
    proactive_recovery_enabled: bool = True
    # Aegis verified state transfer: collect HMAC-signed (tag, value-
    # digest) manifests from a quorum of active replicas before seeding;
    # the recovering node accepts only entries attested by >= f+1 distinct
    # signers (f+1 derived as 2*quorum - n_active, the BFT quorum-
    # intersection bound). Off = the reference's single-spare trust.
    verified_transfer: bool = True
    manifest_timeout: float = 2.0
    # keys per StateChunk frame: large repositories stream as bounded
    # frames instead of one giant Sleep payload
    state_chunk_keys: int = 256
    # intranet secret for verifying manifest HMACs at collection time
    # (the recovering node re-verifies them independently)
    abd_mac_secret: bytes = b"intranet-abd-secret"
    debug: bool = False


class BFTSupervisor:
    def __init__(
        self,
        addr: str,
        active: list[str],
        sentinent: list[str],
        net: Transport,
        config: SupervisorConfig | None = None,
        redeploy: Optional[Callable[[str], Awaitable[None]]] = None,
        rng: random.Random | None = None,
    ):
        self.addr = addr
        self.net = net
        self.cfg = config or SupervisorConfig()
        self.active: list[tuple[str, int]] = [(a, time.monotonic_ns()) for a in active]
        self.sentinent: list[str] = list(sentinent)
        self.nonces: set[int] = set()
        self.quorum: dict[str, set[str]] = {}
        self.redeploy = redeploy
        self._rng = rng or random.Random()
        self._pending: dict[str, asyncio.Future] = {}
        self._task: Optional[asyncio.Task] = None
        self._recovering: set[str] = set()  # endpoints with recovery in flight
        # recovery-complete hook: set whenever NO recovery is in flight.
        # Event-driven waiters (tests, graceful stop) use this instead of
        # sleeping-and-hoping — cancelling a recovery mid-swap tears
        # membership (spare promoted, offender not yet demoted).
        self._idle = asyncio.Event()
        self._idle.set()
        self._inflight: Optional[asyncio.Task] = None  # proactive recover task
        # consecutive unreachability strikes (Awake / post-redeploy Sleep
        # timeouts). One timeout may be transient (slow restart, supervisor-
        # side blip), so nodes are only DROPPED from membership after
        # DROP_STRIKES consecutive failures; any successful contact clears
        # the count. Least-struck spares are preferred for recovery.
        self._strikes: dict[str, int] = {}
        # manifest collections in flight: request nonce -> (future,
        # sender -> StateDigest, target reply count)
        self._manifest_collects: dict[int, tuple] = {}
        net.register(addr, self.handle)

    # ----------------------------------------------------------- life cycle

    def start(self) -> None:
        if self.cfg.proactive_recovery_enabled and self._task is None:
            self._task = supervised_task(self._proactive_loop(),
                                         name="supervisor.proactive")

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        # graceful: a recovery the loop had in flight keeps running under
        # the shield below — await it so stop() never tears membership
        # mid-swap (promoted spare without the offender demoted). Bounded
        # by the recovery path's own timeouts.
        inflight = self._inflight
        if inflight is not None and not inflight.done():
            try:
                await inflight
            except Exception:  # recovery failures are already logged
                pass
        self._inflight = None

    async def wait_recovery_idle(self, timeout: float = 10.0) -> bool:
        """Event-driven recovery-complete hook: resolves once no recovery
        (proactive OR suspicion-quorum-driven) is in flight. Returns False
        on timeout instead of raising — callers decide how loud to be."""
        try:
            await asyncio.wait_for(self._idle.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def _proactive_loop(self) -> None:
        await asyncio.sleep(self.cfg.proactive_recovery_warmup)
        while True:
            if self.active:
                oldest, _ = min(self.active, key=lambda r: r[1])
                if self.cfg.debug:
                    log.info("proactively recovering %s", oldest)
                # shield: cancelling this loop (stop()) must not cancel a
                # swap mid-flight — stop() awaits the task instead
                rec = supervised_task(self.recover(oldest),
                                      name=f"supervisor.recover:{oldest}")
                self._inflight = rec
                try:
                    await asyncio.shield(rec)
                finally:
                    if rec.done():
                        self._inflight = None
            await asyncio.sleep(self.cfg.proactive_recovery_interval)

    # ------------------------------------------------------------- messages

    async def handle(self, sender: str, msg) -> None:
        match msg:
            case M.RequestReplicas():
                # freshest half of the active list, minimum one
                by_age = sorted(self.active, key=lambda r: r[1], reverse=True)
                take = max(1, len(by_age) // 2)
                self.net.send(
                    self.addr, sender, M.ActiveReplicas([a for a, _ in by_age[:take]])
                )

            case M.Suspect(replica, nonce):
                if nonce in self.nonces:
                    return
                self.nonces.add(nonce)
                voters = self.quorum.setdefault(replica, set())
                voters.add(sender)
                if len(voters) >= self.cfg.quorum_size:
                    if self.cfg.debug:
                        log.info("replica %s suspected faulty; recovering", replica)
                    # a suspicion quorum IS a fault event: freeze the
                    # telemetry that led here before recovery churns it
                    tracer.event("supervisor.suspicion_quorum",
                                 replica=replica, voters=len(voters))
                    metrics.inc(
                        "dds_suspicion_quorums_total",
                        replica=replica.rsplit("/", 1)[-1],
                        help="suspicion quorums reached (recovery triggers)",
                    )
                    await flight.record_async(
                        "suspicion_quorum", replica=replica,
                        voters=sorted(voters),
                    )
                    # clear the vote tally NOW so votes landing while the
                    # recovery awaits don't re-trigger it
                    self.quorum[replica] = set()
                    await self.recover(replica)

            case M.State(_, _) | M.Complying():
                fut = self._pending.pop(f"{type(msg).__name__}:{sender}", None)
                if fut is not None and not fut.done():
                    fut.set_result(msg)

            case M.StateDigest(manifest, nonce, signature):
                coll = self._manifest_collects.get(nonce)
                if coll is None:
                    return
                fut, votes, target = coll
                if sender in votes:
                    return
                # verify at collection time too (the recovering node
                # re-verifies independently); an invalid HMAC is dropped
                # and never counted toward the quorum
                if not sigs.validate_manifest_signature(
                    self.cfg.abd_mac_secret, sender, manifest, nonce, signature
                ):
                    log.warning("dropping StateDigest with bad HMAC from %s",
                                sender)
                    return
                votes[sender] = msg
                if len(votes) >= target and not fut.done():
                    fut.set_result(None)

    def _expect(self, dest: str, reply_type: str) -> asyncio.Future:
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[f"{reply_type}:{dest}"] = fut
        return fut

    async def _await_reply(self, dest: str, reply_type: str,
                           fut: asyncio.Future, timeout: float):
        try:
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._pending.pop(f"{reply_type}:{dest}", None)

    async def _ask(self, dest: str, msg, reply_type: str, timeout: float):
        fut = self._expect(dest, reply_type)
        self.net.send(self.addr, dest, msg)
        return await self._await_reply(dest, reply_type, fut, timeout)

    # ------------------------------------------------------------- recovery

    DROP_STRIKES = 3

    def _strike(self, endpoint: str, why: str) -> bool:
        """Record an unreachability strike; True = threshold reached and
        the endpoint should be dropped from membership (loud warning)."""
        self._strikes[endpoint] = self._strikes.get(endpoint, 0) + 1
        if self._strikes[endpoint] >= self.DROP_STRIKES:
            log.warning(
                "replica %s %s (%d consecutive failures); dropping it from "
                "membership (operator action required)",
                endpoint, why, self._strikes[endpoint],
            )
            self._strikes.pop(endpoint, None)
            return True
        log.warning(
            "replica %s %s (strike %d/%d)",
            endpoint, why, self._strikes[endpoint], self.DROP_STRIKES,
        )
        return False

    def _support(self) -> int:
        """Distinct-signer threshold for one verified entry: the quorum-
        intersection bound 2q - n equals f+1 in a canonically-sized BFT
        topology (q = ceil((n+f+1)/2)), so any completed write's quorum
        intersects any manifest quorum in >= f+1 replicas — at least one
        honest — making the attested (tag, digest) unforgeable by any f."""
        return max(1, 2 * self.cfg.quorum_size - len(self.active))

    async def _collect_manifests(self, exclude: set) -> tuple | None:
        """Broadcast StateDigestRequest to the active replicas (minus
        `exclude`) and gather a quorum of signed manifests. Returns
        (digests, support) ready to relay in a SleepBegin, or None when
        fewer than `support` replicas attested within the timeout (a
        verified seed would then reject everything — degrade loudly)."""
        support = self._support()
        targets = [a for a, _ in self.active if a not in exclude]
        if not targets:
            return None
        target_count = min(len(targets), self.cfg.quorum_size)
        nonce = sigs.generate_nonce()
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        votes: dict[str, M.StateDigest] = {}
        self._manifest_collects[nonce] = (fut, votes, target_count)
        for t in targets:
            self.net.send(self.addr, t, M.StateDigestRequest(nonce))
        try:
            await asyncio.wait_for(fut, self.cfg.manifest_timeout)
        except asyncio.TimeoutError:
            pass
        finally:
            self._manifest_collects.pop(nonce, None)
        if len(votes) < support:
            log.warning(
                "manifest quorum failed: %d/%d replicas attested (need >= %d)",
                len(votes), len(targets), support,
            )
            return None
        digests = [
            [sender, d.manifest, d.nonce, d.signature.hex()]
            for sender, d in votes.items()
        ]
        return digests, support

    async def _probe_spares(self, spares: list[str]) -> dict[str, int]:
        """Freshness per spare = the max tag seq in its signed manifest
        (0 when empty or silent — a silent spare is not *penalized* here;
        the Awake strike path owns unreachability)."""
        fresh = {s: 0 for s in spares}
        if not spares:
            return fresh
        nonce = sigs.generate_nonce()
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        votes: dict[str, M.StateDigest] = {}
        self._manifest_collects[nonce] = (fut, votes, len(spares))
        for s in spares:
            self.net.send(self.addr, s, M.StateDigestRequest(nonce))
        timeout = min(self.cfg.manifest_timeout,
                      self.cfg.sentinent_awake_timeout)
        try:
            await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            pass
        finally:
            self._manifest_collects.pop(nonce, None)
        for sender, d in votes.items():
            if sender in fresh:
                fresh[sender] = max(
                    (int(e[0]) for e in d.manifest.values()), default=0
                )
        return fresh

    async def _seed(self, dest: str, state: M.State, verified: tuple | None,
                    timeout: float):
        """Reseed `dest` with the spare's state and await its Complying.

        Verified path: relay the collected manifest quorum in a SleepBegin
        header, then stream the state as bounded StateChunk frames — the
        node cross-checks every entry against the digest quorum, so the
        spare's State is data, not truth. `verified=None` falls back to
        the legacy single-frame Sleep (reference behavior)."""
        if verified is None:
            return await self._ask(
                dest, M.Sleep(state.data, state.nonces), "Complying", timeout
            )
        digests, support = verified
        session = sigs.generate_nonce()
        items = sorted(state.data.items())
        k = max(1, self.cfg.state_chunk_keys)
        chunks = [dict(items[i:i + k]) for i in range(0, len(items), k)] or [{}]
        fut = self._expect(dest, "Complying")
        self.net.send(
            self.addr, dest,
            M.SleepBegin(digests, session, len(chunks), support,
                         list(state.nonces)),
        )
        for seq, chunk in enumerate(chunks):
            self.net.send(self.addr, dest, M.StateChunk(session, seq, chunk))
        tracer.event("supervisor.seed", dest=dest, chunks=len(chunks),
                     keys=len(items), verified=True)
        return await self._await_reply(dest, "Complying", fut, timeout)

    async def recover(self, byzantine: str) -> None:
        """Swap the suspect with a sentinent spare; reseed or redeploy it.

        Guards (beyond the reference): only ACTIVE replicas are recoverable —
        a suspicion quorum naming an arbitrary endpoint (e.g. a proxy) must
        not consume a spare or redeploy over a non-replica address — and a
        recovery already in flight for the same endpoint (or using the last
        spare) is not re-entered by concurrent votes / the proactive timer.

        Aegis: with verified_transfer on, a quorum of signed state
        manifests is collected FIRST and relayed with the seed, so the
        recovering node never has to trust the single seeding spare; the
        spare itself is chosen freshest-first (max manifest tag seq,
        tie-break random) among the least-struck candidates.
        """
        if byzantine in self._recovering:
            return
        if byzantine not in (a for a, _ in self.active):
            log.warning("refusing to recover non-active endpoint %s", byzantine)
            return
        self._recovering.add(byzantine)
        self._idle.clear()
        spare = None
        tried: set[str] = set()
        with tracer.span("supervisor.recover", victim=byzantine) as span:
            try:
                verified = None
                if self.cfg.verified_transfer:
                    verified = await self._collect_manifests({byzantine})
                    if verified is None:
                        log.warning(
                            "verified state transfer degraded for %s: no "
                            "manifest quorum; seeding UNVERIFIED from a "
                            "single spare", byzantine,
                        )
                        metrics.inc(
                            "dds_recovery_unverified_total",
                            help="recoveries that fell back to single-spare "
                                 "trust (no manifest quorum)",
                        )
                span["verified"] = verified is not None
                freshness = await self._probe_spares(
                    [s for s in self.sentinent if s not in self._recovering]
                ) if self.cfg.verified_transfer else {}
                while True:
                    pool = [
                        s for s in self.sentinent
                        if s not in self._recovering and s not in tried
                    ]
                    if not pool:
                        log.warning(
                            "no (responsive) spare available to recover %s; "
                            "it stays active until a spare returns", byzantine,
                        )
                        return
                    # prefer the least-struck spares (recently-unresponsive
                    # ones are retried only when nothing better remains);
                    # among those, the freshest repository seeds fastest
                    best = min(self._strikes.get(s, 0) for s in pool)
                    candidates = [
                        s for s in pool if self._strikes.get(s, 0) == best
                    ]
                    top = max(freshness.get(s, 0) for s in candidates)
                    spare = self._rng.choice(
                        [s for s in candidates if freshness.get(s, 0) == top]
                    )
                    tried.add(spare)
                    self._recovering.add(spare)
                    try:
                        state = await self._ask(
                            spare, M.Awake(), "State",
                            self.cfg.sentinent_awake_timeout,
                        )
                        self._strikes.pop(spare, None)
                        break
                    except asyncio.TimeoutError:
                        self._recovering.discard(spare)
                        if self._strike(spare, "did not wake up"):
                            self.sentinent.remove(spare)
                        spare = None

                span["seeder"] = spare
                tracer.event("supervisor.seeder", victim=byzantine,
                             seeder=spare, freshness=freshness.get(spare, 0))

                # promote the spare
                self.sentinent.remove(spare)
                self.active.append((spare, time.monotonic_ns()))

                # kill (-> guardian restart) and demote the offender
                self.net.send(self.addr, byzantine, M.Kill())
                self.active = [r for r in self.active if r[0] != byzantine]

                try:
                    await self._seed(
                        byzantine, state, verified,
                        self.cfg.sentinent_awake_timeout,
                    )
                    self._strikes.pop(byzantine, None)
                    self.sentinent.append(byzantine)
                    self.quorum[byzantine] = set()
                except asyncio.TimeoutError:
                    # host is dead: redeploy a fresh replica at the endpoint
                    if self.redeploy is None:
                        log.warning("replica %s dead and no redeploy hook",
                                    byzantine)
                        return
                    if self.cfg.debug:
                        log.info("replica %s crashed; rebooting", byzantine)
                    await self.redeploy(byzantine)
                    try:
                        await self._seed(
                            byzantine, state, verified,
                            self.cfg.crashed_recovery_timeout,
                        )
                        self._strikes.pop(byzantine, None)
                    except asyncio.TimeoutError:
                        # One miss may just be a slow restart: keep it as a
                        # (struck) spare so it self-heals when it comes back.
                        # Persistent unreachability accrues strikes — here or
                        # when it is later retried as a spare — and only then
                        # is it dropped, so phantoms cannot pin recoveries
                        # forever yet a transient blip costs nothing.
                        if self._strike(byzantine, "never complied after reboot"):
                            self.quorum[byzantine] = set()
                            return
                    self.sentinent.append(byzantine)
                    self.quorum[byzantine] = set()
            finally:
                self._recovering.discard(byzantine)
                if spare is not None:
                    self._recovering.discard(spare)
                if not self._recovering:
                    self._idle.set()
