"""Membership + failure manager: suspicion quorums, spares, proactive recovery.

Counterpart of `dds/core/BFTSupervisor.scala`: tracks active/sentinent
replica lists, dedupes `Suspect` votes by nonce, recovers a replica once a
quorum of distinct voters suspects it, proactively recovers the oldest
active replica on a timer, and serves proxies the freshest half of the
active list.

Recovery (BFTSupervisor.scala:97-153): wake a random sentinent spare
(`Awake` -> `State{data, nonces}`), promote it to active; `Kill` the
offender (guardian-restart semantics) and re-seed it with the spare's state
via `Sleep` -> `Complying`, demoting it to sentinent. If the offender's
host is dead (ask timeout), redeploy a fresh replica at the same endpoint
through the injected factory and seed that instead. Nodes that prove
unreachable — a spare that never Awakes, or an offender that never
Complies after redeploy — accrue strikes; one miss is treated as transient
(slow restart, supervisor-side blip) and the node stays a (deprioritized)
spare, but DROP_STRIKES consecutive failures drop it from membership with
a loud warning rather than keeping a phantom that pins future recoveries
(deviation from the reference, which would retry forever); the operator
restores dropped nodes explicitly. Successful contact clears strikes.

Deviations (documented): suspicion voters are the *senders* of Suspect
votes (the reference seeds the voter set with the suspected node itself,
`BFTSupervisor.scala:79` — a bookkeeping bug); `RequestReplicas` returns at
least one endpoint even with a single active replica.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from dataclasses import dataclass
from typing import Awaitable, Callable, Optional

from dds_tpu.core import messages as M
from dds_tpu.core.transport import Transport
from dds_tpu.obs.flight import flight
from dds_tpu.obs.metrics import metrics
from dds_tpu.utils.trace import tracer

log = logging.getLogger("dds.supervisor")


@dataclass
class SupervisorConfig:
    quorum_size: int = 5
    proactive_recovery_warmup: float = 5.0
    proactive_recovery_interval: float = 7.0
    sentinent_awake_timeout: float = 5.0
    crashed_recovery_timeout: float = 12.0
    proactive_recovery_enabled: bool = True
    debug: bool = False


class BFTSupervisor:
    def __init__(
        self,
        addr: str,
        active: list[str],
        sentinent: list[str],
        net: Transport,
        config: SupervisorConfig | None = None,
        redeploy: Optional[Callable[[str], Awaitable[None]]] = None,
        rng: random.Random | None = None,
    ):
        self.addr = addr
        self.net = net
        self.cfg = config or SupervisorConfig()
        self.active: list[tuple[str, int]] = [(a, time.monotonic_ns()) for a in active]
        self.sentinent: list[str] = list(sentinent)
        self.nonces: set[int] = set()
        self.quorum: dict[str, set[str]] = {}
        self.redeploy = redeploy
        self._rng = rng or random.Random()
        self._pending: dict[str, asyncio.Future] = {}
        self._task: Optional[asyncio.Task] = None
        self._recovering: set[str] = set()  # endpoints with recovery in flight
        # consecutive unreachability strikes (Awake / post-redeploy Sleep
        # timeouts). One timeout may be transient (slow restart, supervisor-
        # side blip), so nodes are only DROPPED from membership after
        # DROP_STRIKES consecutive failures; any successful contact clears
        # the count. Least-struck spares are preferred for recovery.
        self._strikes: dict[str, int] = {}
        net.register(addr, self.handle)

    # ----------------------------------------------------------- life cycle

    def start(self) -> None:
        if self.cfg.proactive_recovery_enabled and self._task is None:
            self._task = asyncio.ensure_future(self._proactive_loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _proactive_loop(self) -> None:
        await asyncio.sleep(self.cfg.proactive_recovery_warmup)
        while True:
            if self.active:
                oldest, _ = min(self.active, key=lambda r: r[1])
                if self.cfg.debug:
                    log.info("proactively recovering %s", oldest)
                await self.recover(oldest)
            await asyncio.sleep(self.cfg.proactive_recovery_interval)

    # ------------------------------------------------------------- messages

    async def handle(self, sender: str, msg) -> None:
        match msg:
            case M.RequestReplicas():
                # freshest half of the active list, minimum one
                by_age = sorted(self.active, key=lambda r: r[1], reverse=True)
                take = max(1, len(by_age) // 2)
                self.net.send(
                    self.addr, sender, M.ActiveReplicas([a for a, _ in by_age[:take]])
                )

            case M.Suspect(replica, nonce):
                if nonce in self.nonces:
                    return
                self.nonces.add(nonce)
                voters = self.quorum.setdefault(replica, set())
                voters.add(sender)
                if len(voters) >= self.cfg.quorum_size:
                    if self.cfg.debug:
                        log.info("replica %s suspected faulty; recovering", replica)
                    # a suspicion quorum IS a fault event: freeze the
                    # telemetry that led here before recovery churns it
                    tracer.event("supervisor.suspicion_quorum",
                                 replica=replica, voters=len(voters))
                    metrics.inc(
                        "dds_suspicion_quorums_total",
                        replica=replica.rsplit("/", 1)[-1],
                        help="suspicion quorums reached (recovery triggers)",
                    )
                    flight.record(
                        "suspicion_quorum", replica=replica,
                        voters=sorted(voters),
                    )
                    # clear the vote tally NOW so votes landing while the
                    # recovery awaits don't re-trigger it
                    self.quorum[replica] = set()
                    await self.recover(replica)

            case M.State(_, _) | M.Complying():
                fut = self._pending.pop(f"{type(msg).__name__}:{sender}", None)
                if fut is not None and not fut.done():
                    fut.set_result(msg)

    async def _ask(self, dest: str, msg, reply_type: str, timeout: float):
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[f"{reply_type}:{dest}"] = fut
        self.net.send(self.addr, dest, msg)
        try:
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._pending.pop(f"{reply_type}:{dest}", None)

    # ------------------------------------------------------------- recovery

    DROP_STRIKES = 3

    def _strike(self, endpoint: str, why: str) -> bool:
        """Record an unreachability strike; True = threshold reached and
        the endpoint should be dropped from membership (loud warning)."""
        self._strikes[endpoint] = self._strikes.get(endpoint, 0) + 1
        if self._strikes[endpoint] >= self.DROP_STRIKES:
            log.warning(
                "replica %s %s (%d consecutive failures); dropping it from "
                "membership (operator action required)",
                endpoint, why, self._strikes[endpoint],
            )
            self._strikes.pop(endpoint, None)
            return True
        log.warning(
            "replica %s %s (strike %d/%d)",
            endpoint, why, self._strikes[endpoint], self.DROP_STRIKES,
        )
        return False

    async def recover(self, byzantine: str) -> None:
        """Swap the suspect with a sentinent spare; reseed or redeploy it.

        Guards (beyond the reference): only ACTIVE replicas are recoverable —
        a suspicion quorum naming an arbitrary endpoint (e.g. a proxy) must
        not consume a spare or redeploy over a non-replica address — and a
        recovery already in flight for the same endpoint (or using the last
        spare) is not re-entered by concurrent votes / the proactive timer.
        """
        if byzantine in self._recovering:
            return
        if byzantine not in (a for a, _ in self.active):
            log.warning("refusing to recover non-active endpoint %s", byzantine)
            return
        self._recovering.add(byzantine)
        spare = None
        tried: set[str] = set()
        try:
            while True:
                pool = [
                    s for s in self.sentinent
                    if s not in self._recovering and s not in tried
                ]
                if not pool:
                    log.warning(
                        "no (responsive) spare available to recover %s; "
                        "it stays active until a spare returns", byzantine,
                    )
                    return
                # prefer the least-struck spares: recently-unresponsive
                # ones are retried only when nothing better remains
                best = min(self._strikes.get(s, 0) for s in pool)
                spare = self._rng.choice(
                    [s for s in pool if self._strikes.get(s, 0) == best]
                )
                tried.add(spare)
                self._recovering.add(spare)
                try:
                    state = await self._ask(
                        spare, M.Awake(), "State",
                        self.cfg.sentinent_awake_timeout,
                    )
                    self._strikes.pop(spare, None)
                    break
                except asyncio.TimeoutError:
                    self._recovering.discard(spare)
                    if self._strike(spare, "did not wake up"):
                        self.sentinent.remove(spare)
                    spare = None

            # promote the spare
            self.sentinent.remove(spare)
            self.active.append((spare, time.monotonic_ns()))

            # kill (-> guardian restart) and demote the offender
            self.net.send(self.addr, byzantine, M.Kill())
            self.active = [r for r in self.active if r[0] != byzantine]

            try:
                await self._ask(
                    byzantine,
                    M.Sleep(state.data, state.nonces),
                    "Complying",
                    self.cfg.sentinent_awake_timeout,
                )
                self._strikes.pop(byzantine, None)
                self.sentinent.append(byzantine)
                self.quorum[byzantine] = set()
            except asyncio.TimeoutError:
                # host is dead: redeploy a fresh replica at the same endpoint
                if self.redeploy is None:
                    log.warning("replica %s dead and no redeploy hook", byzantine)
                    return
                if self.cfg.debug:
                    log.info("replica %s crashed; rebooting", byzantine)
                await self.redeploy(byzantine)
                try:
                    await self._ask(
                        byzantine,
                        M.Sleep(state.data, state.nonces),
                        "Complying",
                        self.cfg.crashed_recovery_timeout,
                    )
                    self._strikes.pop(byzantine, None)
                except asyncio.TimeoutError:
                    # One miss may just be a slow restart: keep it as a
                    # (struck) spare so it self-heals when it comes back.
                    # Persistent unreachability accrues strikes — here or
                    # when it is later retried as a spare — and only then
                    # is it dropped, so phantoms cannot pin recoveries
                    # forever yet a transient blip costs nothing.
                    if self._strike(byzantine, "never complied after reboot"):
                        self.quorum[byzantine] = set()
                        return
                self.sentinent.append(byzantine)
                self.quorum[byzantine] = set()
        finally:
            self._recovering.discard(byzantine)
            if spare is not None:
                self._recovering.discard(spare)
