"""Optional snapshot-to-disk for replica state.

The reference has no disk persistence: durability comes from replication
only, with live `State(data, nonces)` transfer re-seeding recovered nodes
(SURVEY.md §5.4, `BFTABDNode.scala:368-375,413-416`). We keep that model
— snapshots are an *additional* cold-start accelerator, not the source of
truth: a restored replica rejoins with a possibly-stale repository and the
ABD read/write-back protocol repairs it per-key (same argument as spare
promotion).

Format: one JSON file per replica: {"repository": {key: [seq, id, value]},
"expired_nonces": [...]} — value is the JSON row (list) or null.
"""

from __future__ import annotations

import json
import os
import pathlib

from dds_tpu.core import messages as M
from dds_tpu.core.replica import BFTABDNode


def save_replica(node: BFTABDNode, directory: str | os.PathLike) -> pathlib.Path:
    """Write the node's repository + anti-replay state atomically."""
    d = pathlib.Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    path = d / f"{node.name}.snapshot.json"
    state = {
        "repository": {
            k: [t.seq, t.id, v] for k, (t, v) in node.repository.items()
        },
        "expired_nonces": sorted(
            n for n, expired in node.incoming.items() if expired
        ),
    }
    tmp = path.with_suffix(".tmp")
    tmp.write_text(json.dumps(state))
    os.replace(tmp, path)
    return path


def load_replica(node: BFTABDNode, directory: str | os.PathLike) -> bool:
    """Restore a prior snapshot into the node, if one exists."""
    path = pathlib.Path(directory) / f"{node.name}.snapshot.json"
    if not path.exists():
        return False
    state = json.loads(path.read_text())
    node.repository = {
        k: (M.ABDTag(seq, tid), v)
        for k, (seq, tid, v) in (
            (k, tuple(entry)) for k, entry in state["repository"].items()
        )
    }
    for n in state.get("expired_nonces", []):
        node.incoming[int(n)] = True
    return True


def save_all(replicas: dict[str, BFTABDNode], directory: str | os.PathLike) -> int:
    for node in replicas.values():
        save_replica(node, directory)
    return len(replicas)


def load_all(replicas: dict[str, BFTABDNode], directory: str | os.PathLike) -> int:
    return sum(1 for node in replicas.values() if load_replica(node, directory))
