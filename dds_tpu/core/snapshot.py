"""Crash-safe authenticated snapshots (v2) for replica state.

The reference has no disk persistence: durability comes from replication
only, with live `State(data, nonces)` transfer re-seeding recovered nodes
(SURVEY.md §5.4, `BFTABDNode.scala:368-375,413-416`). We keep that model
— snapshots are an *additional* cold-start accelerator, not the source of
truth: a restored replica rejoins with a possibly-stale repository and the
Merkle anti-entropy loop (core/antientropy.py) converges it without
waiting for client reads.

v2 format — one file per generation, `{name}.snapshot.{gen:08d}.json`:

    <canonical JSON body>\n<hmac-sha256 hex footer>\n

    body = {"v": 2, "generation": g, "saved_at": unix-ts,
            "repository": {key: [tag.seq, tag.id, value]},
            "nonces": {str(nonce): expired_bool}}

- The footer authenticates the body with a key derived (derive_secret)
  from the intranet secret plus, when provisioned, the node's transport
  key file (utils/nodeauth) — a snapshot forged or flipped on disk fails
  verification at load and is QUARANTINED (renamed `*.corrupt`), never
  loaded and never allowed to crash `run.launch`.
- Writes are fsync-before-rename (file *and* directory), so a crash
  mid-save leaves either the previous generation or the complete new one.
- Generations rotate keep-N: load walks newest-first and falls back to
  the next-older generation when one fails verification.
- The FULL anti-replay nonce map persists (v1 kept only expired nonces,
  silently dropping in-flight ones across a restore — a replay window).

v1 files (`{name}.snapshot.json`, no footer) are still readable for
migration — unauthenticated, with a loud warning; corrupt/truncated ones
are quarantined as `{name}.snapshot.corrupt`.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import logging
import os
import pathlib
import re
import time

from dds_tpu.core import messages as M
from dds_tpu.core.replica import BFTABDNode
from dds_tpu.obs.metrics import metrics

log = logging.getLogger("dds.snapshot")

# default derivation base = the default intranet secret, so bare
# save_replica/load_replica calls (tests, tooling) stay self-consistent
# with launch()-derived secrets under a default config
DEFAULT_BASE = b"intranet-abd-secret"

_GEN_RE = re.compile(r"\.snapshot\.(\d{8})\.json$")


def derive_secret(base: bytes = DEFAULT_BASE,
                  node_key_path: str | os.PathLike | None = None,
                  label: bytes = b"dds-snapshot-mac-v2") -> bytes:
    """Snapshot MAC key: HMAC-derived from the intranet secret, mixed with
    the node's transport key file (utils/nodeauth) when one is provisioned
    — per-node keys then yield per-node snapshot keys, so one host's
    snapshot cannot be replanted onto another. `label` domain-separates
    sibling on-disk formats sharing the discipline (Stratum's segment
    store derives with its own label, so a snapshot footer can never
    verify as a segment footer or vice versa)."""
    material = bytes(base)
    if node_key_path:
        p = pathlib.Path(node_key_path)
        if p.exists():
            material += p.read_bytes()
    return hmac.new(material, label, hashlib.sha256).digest()


def write_authenticated(path: pathlib.Path, body: bytes, secret: bytes) -> None:
    """Write `body` + HMAC-SHA256 hex footer crash-safely: tmp file,
    flush + fsync, atomic rename, then directory-fd fsync so the rename
    itself is durable — the v2 snapshot discipline, shared with the
    Stratum segment store (`storage/segment.py`). A crash at any point
    leaves either the previous file or the complete new one."""
    footer = hmac.new(secret, body, hashlib.sha256).hexdigest().encode()
    tmp = path.with_name(path.name + ".tmp")
    with open(tmp, "wb") as f:
        f.write(body + b"\n" + footer + b"\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    try:
        # the rename itself must be durable, or a crash can resurface the
        # old directory entry with the new data gone
        dfd = os.open(path.parent, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:  # pragma: no cover - fs-dependent
        pass


def read_authenticated(path: pathlib.Path, secret: bytes) -> bytes:
    """Verify + strip the HMAC footer; returns the body bytes. Raises
    ValueError on truncation or footer mismatch (corrupt or forged)."""
    raw = path.read_bytes()
    body, sep, footer = raw.rstrip(b"\n").rpartition(b"\n")
    if not sep or not body:
        raise ValueError("truncated (no footer)")
    if not hmac.compare_digest(
        hmac.new(secret, body, hashlib.sha256).hexdigest().encode(),
        footer.strip(),
    ):
        raise ValueError("HMAC footer mismatch (corrupt or forged)")
    return body


def _generations(directory: pathlib.Path, name: str) -> list[tuple[int, pathlib.Path]]:
    """(gen, path) for every v2 generation file of `name`, newest first."""
    out = []
    for p in directory.glob(f"{name}.snapshot.*.json"):
        m = _GEN_RE.search(p.name)
        if m:
            out.append((int(m.group(1)), p))
    return sorted(out, reverse=True)


def _quarantine(path: pathlib.Path, reason: str, replica: str) -> None:
    """Rename a bad snapshot aside (`*.corrupt`) instead of loading it or
    letting its parse error abort boot."""
    target = path.with_name(
        path.name[:-len(".json")] + ".corrupt"
        if path.name.endswith(".json") else path.name + ".corrupt"
    )
    log.warning("quarantining snapshot %s -> %s (%s)", path, target.name, reason)
    metrics.inc(
        "dds_snapshot_verify_failures_total", replica=replica,
        help="snapshot files quarantined at load (corrupt/truncated/forged)",
    )
    try:
        os.replace(path, target)
    except OSError as e:  # pragma: no cover - fs-dependent
        log.warning("could not quarantine %s: %s", path, e)


def save_replica(node: BFTABDNode, directory: str | os.PathLike,
                 secret: bytes | None = None, keep: int = 3) -> pathlib.Path:
    """Write one authenticated generation of the node's state; prune to
    the newest `keep` generations."""
    secret = secret or derive_secret()
    d = pathlib.Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    gens = _generations(d, node.name)
    gen = (gens[0][0] + 1) if gens else 1
    state = {
        "v": 2,
        "generation": gen,
        "saved_at": time.time(),
        "repository": {
            k: [t.seq, t.id, v] for k, (t, v) in node.repository.items()
        },
        # the FULL anti-replay map: in-flight (unexpired) nonces must
        # survive a restore or they become replayable
        "nonces": {str(n): bool(e) for n, e in node.incoming.items()},
    }
    body = json.dumps(state, sort_keys=True, separators=(",", ":")).encode()
    path = d / f"{node.name}.snapshot.{gen:08d}.json"
    write_authenticated(path, body, secret)
    for _, old in _generations(d, node.name)[max(1, keep):]:
        try:
            old.unlink()
        except OSError:  # pragma: no cover - fs-dependent
            pass
    node.snapshot_meta = {"generation": gen, "saved_at": state["saved_at"]}
    metrics.set("dds_snapshot_generation", gen, replica=node.name,
                help="latest snapshot generation written or loaded")
    return path


def _read_v2(path: pathlib.Path, secret: bytes) -> dict:
    state = json.loads(read_authenticated(path, secret))
    if state.get("v") != 2:
        raise ValueError(f"unsupported snapshot version {state.get('v')!r}")
    return state


def _install(node: BFTABDNode, state: dict, generation: int) -> None:
    node._install_repository({
        k: (M.ABDTag(int(seq), str(tid)), v)
        for k, (seq, tid, v) in (
            (k, tuple(entry)) for k, entry in state["repository"].items()
        )
    })
    for n, expired in (state.get("nonces") or {}).items():
        node.incoming[int(n)] = bool(expired)
    for n in state.get("expired_nonces", []):  # v1 files
        node.incoming[int(n)] = True
    node.snapshot_meta = {
        "generation": generation,
        "saved_at": state.get("saved_at"),
        "loaded": True,
    }
    metrics.set("dds_snapshot_generation", generation, replica=node.name,
                help="latest snapshot generation written or loaded")


def load_replica(node: BFTABDNode, directory: str | os.PathLike,
                 secret: bytes | None = None) -> bool:
    """Restore the newest VERIFIED snapshot generation, quarantining every
    corrupt/truncated/forged file it walks past; never raises for bad
    files, so one flipped byte cannot abort `run.launch`."""
    secret = secret or derive_secret()
    d = pathlib.Path(directory)
    for gen, path in _generations(d, node.name):
        try:
            state = _read_v2(path, secret)
        except (OSError, ValueError, json.JSONDecodeError, UnicodeDecodeError) as e:
            _quarantine(path, str(e), node.name)
            continue
        _install(node, state, gen)
        return True
    legacy = d / f"{node.name}.snapshot.json"
    if legacy.exists():
        try:
            state = json.loads(legacy.read_text())
            if not isinstance(state, dict) or "repository" not in state:
                raise ValueError("not a v1 snapshot object")
        except (OSError, ValueError, json.JSONDecodeError, UnicodeDecodeError) as e:
            _quarantine(legacy, str(e), node.name)
            return False
        log.warning(
            "loaded UNAUTHENTICATED v1 snapshot %s; the next save upgrades "
            "it to the authenticated v2 format", legacy,
        )
        _install(node, state, int(state.get("generation", 0)))
        return True
    return False


def save_all(replicas: dict[str, BFTABDNode], directory: str | os.PathLike,
             secret: bytes | None = None, keep: int = 3) -> int:
    for node in replicas.values():
        save_replica(node, directory, secret=secret, keep=keep)
    return len(replicas)


def load_all(replicas: dict[str, BFTABDNode], directory: str | os.PathLike,
             secret: bytes | None = None) -> int:
    return sum(
        1 for node in replicas.values()
        if load_replica(node, directory, secret=secret)
    )
