"""Typed Byzantine-failure exceptions, counterpart of `dds/exceptions/`."""


class ByzantineError(Exception):
    """Base class for protocol-violation failures detected at the proxy."""


class ByzFailedNonceChallengeError(ByzantineError):
    """Reply nonce did not match the expected challenge (nonce + increment)."""


class ByzInvalidSignatureError(ByzantineError):
    """HMAC verification failed on a reply."""


class ByzInvalidKeyError(ByzantineError):
    """Reply echoed a different record key than requested."""


class ByzUnknownReplyError(ByzantineError):
    """Reply type made no sense for the outstanding request."""


class AllBreakersOpenError(Exception):
    """Every trusted coordinator's circuit breaker is open AND none will
    half-open within the caller's remaining budget — the attempt is
    provably futile, so the storage layer degrades immediately instead of
    burning the Deadline on timeouts against targets it already knows are
    refusing traffic (Bulwark fast-fail, core/admission). NOT a
    ByzantineError: nobody misbehaved, the fabric is just down. `eta` is
    the nearest half-open probe in seconds — the REST edge derives
    Retry-After from it."""

    def __init__(self, eta: float, targets: int = 0):
        self.eta = eta
        self.targets = targets
        super().__init__(
            f"all {targets} trusted coordinators have open breakers "
            f"(nearest half-open probe in {eta:.3f}s)"
        )


class WrongShardError(Exception):
    """The addressed replica group does not own the key under its current
    shard map (Constellation epoch fencing, dds_tpu/shard). NOT a
    ByzantineError: the replica behaved correctly — the caller's shard map
    is stale (or a reshard is mid-flight). The proxy refreshes its map and
    retries under the existing Deadline budget; no suspicion accrues."""

    def __init__(self, key: str, replica_epoch: int | None = None,
                 sent_epoch: int | None = None):
        self.key = key
        self.replica_epoch = replica_epoch
        self.sent_epoch = sent_epoch
        super().__init__(
            f"key {key[:16]}... not owned by addressed group "
            f"(replica epoch {replica_epoch}, request epoch {sent_epoch})"
        )
