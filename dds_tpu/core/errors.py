"""Typed Byzantine-failure exceptions, counterpart of `dds/exceptions/`."""


class ByzantineError(Exception):
    """Base class for protocol-violation failures detected at the proxy."""


class ByzFailedNonceChallengeError(ByzantineError):
    """Reply nonce did not match the expected challenge (nonce + increment)."""


class ByzInvalidSignatureError(ByzantineError):
    """HMAC verification failed on a reply."""


class ByzInvalidKeyError(ByzantineError):
    """Reply echoed a different record key than requested."""


class ByzUnknownReplyError(ByzantineError):
    """Reply type made no sense for the outstanding request."""
