"""Bulwark: SLO-driven admission control and priority load shedding.

Everything upstream of this module *observes* overload: the SLO engine
(obs/slo) tracks error-budget burn, breakers (utils/retry) track dead
coordinators, the flight recorder freezes the evidence. Nothing *decides*
— under sustained overload every request still burns its full Deadline
budget before 503ing, and one hot tenant starves the rest. Bulwark is the
decision loop, sitting at the REST edge BEFORE a Deadline is minted:

- `TokenBucket` per (tenant, priority class): a request that exceeds its
  tenant's refill rate is rejected in microseconds with 429 and a
  Retry-After equal to the bucket's actual refill ETA — the hot tenant
  pays, everyone else keeps their budget. With Bastion the buckets are
  *weighted-fair*: when a class's aggregate demand exceeds its configured
  rate, each active tenant's refill contracts to its weight share of the
  class rate (work-conserving — under-subscribed classes leave every
  tenant at the full rate), so a flooding tenant cannot monopolize a
  class simply by arriving first.
- Per-tenant burn-driven shedding: the controller tracks per-(tenant,
  class) outcomes in the evaluation window; when the fleet's SLO burn
  alert fires AND one tenant owns at least `tenant_burn_threshold` of
  the window's bad outcomes, THAT tenant is shed (429s for its sheddable
  classes) instead of ratcheting the whole fleet — a distressed tenant
  sheds itself, not the fleet. Tenant state is bounded
  (`max_tracked_tenants`; beyond it tenants share an "overflow" bucket
  and attribution coarsens, but requests still serve).
- `AdmissionController`: a shedding ratchet driven by the SLO engine's
  multiwindow burn alerts and the breaker census. Distress raises the
  shed level one class at a time (lowest priority first: background,
  then aggregates; interactive only if `max_shed_level` allows), each
  rejection a microsecond 503; recovery steps DOWN one level only after
  `shed_hold` consecutive healthy evaluations — the hysteresis that
  keeps a marginal system from flapping. Every transition is
  flight-recorded and counted (`dds_admission_*`).
- `AdaptiveCoalescer`: sizes the proxy's fold-coalescing window from the
  OBSERVED fold arrival rate instead of a fixed knob — the BTS insight
  (arxiv 2112.15479) that HE throughput comes from keeping batch shapes
  full and steady: under load the window stretches until an expected
  `target_folds` arrivals fit (so device batches stay full), and snaps
  back to the base window when traffic goes idle (so a lone aggregate
  never waits for company that is not coming).

The controller imports no config tree and no SLO engine — the burn and
breaker signals arrive as injected callables, and every class takes an
injectable clock, so the tests (tests/test_admission.py) run the whole
shed/unshed state machine on a fake clock.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass
from typing import Callable, Iterable, Optional

from dds_tpu.obs.metrics import metrics
from dds_tpu.utils.trace import tracer

__all__ = [
    "CLASSES", "route_class",
    "TokenBucket", "Decision", "AdmissionController", "AdaptiveCoalescer",
]

# Priority classes, highest first. The shed ratchet drops them from the
# RIGHT: level 1 sheds background, level 2 also aggregates, level 3
# (opt-in) shedding interactive means the edge answers nothing but the
# exempt observability routes.
CLASSES = ("interactive", "aggregate", "background")

# Route -> class defaults. Point ops are what a human is waiting on;
# aggregates/search/analytics fan out over the whole store and can be
# recomputed; gossip and anything unrecognized is background.
_INTERACTIVE = frozenset({
    "GetSet", "PutSet", "RemoveSet", "AddElement", "ReadElement",
    "WriteElement", "IsElement", "Sum", "Mult",
})
_AGGREGATE = frozenset({
    "SumAll", "MultAll", "OrderLS", "OrderSL", "Range",
    "SearchEq", "SearchNEq", "SearchGt", "SearchGtEq", "SearchLt",
    "SearchLtEq", "SearchEntry", "SearchEntryOR", "SearchEntryAND",
    "MatVec", "WeightedSum", "GroupBySum",
})


def route_class(route: str, overrides: dict | None = None) -> int:
    """Class index for a route (0 = interactive ... 2 = background)."""
    if overrides:
        name = overrides.get(route)
        if name in CLASSES:
            return CLASSES.index(name)
    if route in _INTERACTIVE:
        return 0
    if route in _AGGREGATE:
        return 1
    return 2


class TokenBucket:
    """Classic token bucket: `rate` tokens/s refill up to `burst` capacity.

    Not thread-safe on its own — the controller serializes access under
    its lock (the REST edge calls from one event loop anyway)."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._last = clock()

    def _refill(self) -> None:
        now = self._clock()
        if now > self._last:
            self._tokens = min(self.burst, self._tokens + (now - self._last) * self.rate)
        self._last = now

    @property
    def tokens(self) -> float:
        self._refill()
        return self._tokens

    def try_acquire(self, n: float = 1.0) -> bool:
        self._refill()
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    def refill_eta(self, n: float = 1.0) -> float:
        """Seconds until `n` tokens will be available (0 = now). This is
        the honest Retry-After for a throttled request — derived from
        refill state, not a config constant."""
        self._refill()
        deficit = n - self._tokens
        if deficit <= 0:
            return 0.0
        if self.rate <= 0:
            return math.inf
        return deficit / self.rate


@dataclass(frozen=True)
class Decision:
    """One admission verdict. `retry_after` is in seconds and already
    derived from real state (bucket refill / breaker ETA / ratchet
    cadence); 0 means the caller should fall back to its config hint."""

    admitted: bool
    status: int = 200
    retry_after: float = 0.0
    reason: str = ""
    klass: str = CLASSES[0]


class AdmissionController:
    """The Bulwark decision loop: per-(tenant, class) token buckets plus
    the shed-level ratchet.

    `alerts` yields the routes whose multiwindow SLO burn alert is firing
    (SloEngine.alerts); `breakers` returns `(coordinator_count,
    open_etas)` — how many coordinators the storage layer trusts and the
    half-open ETA of each one whose breaker currently refuses traffic
    (AbdClient/ShardRouter.breaker_census). Both are re-read on every
    evaluation, never cached."""

    def __init__(
        self,
        rates: dict[str, tuple[float, float]] | None = None,
        class_overrides: dict[str, str] | None = None,
        eval_interval: float = 1.0,
        shed_hold: int = 3,
        max_shed_level: int = 2,
        breaker_shed_fraction: float = 0.5,
        tenant_header: str = "x-dds-tenant",
        alerts: Optional[Callable[[], Iterable[str]]] = None,
        breakers: Optional[Callable[[], tuple[int, list[float]]]] = None,
        clock: Callable[[], float] = time.monotonic,
        tenant_weights: dict[str, float] | None = None,
        default_weight: float = 1.0,
        tenant_burn_threshold: float = 0.5,
        tenant_shed_hold: int = 3,
        max_tracked_tenants: int = 1024,
    ):
        # class name -> (rate, burst); a missing class is unthrottled
        self.rates = dict(rates or {})
        self.class_overrides = dict(class_overrides or {})
        self.eval_interval = float(eval_interval)
        self.shed_hold = int(shed_hold)
        self.max_shed_level = max(0, min(int(max_shed_level), len(CLASSES)))
        self.breaker_shed_fraction = float(breaker_shed_fraction)
        self.tenant_header = tenant_header
        self._alerts = alerts or (lambda: ())
        self._breakers = breakers or (lambda: (0, []))
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: dict[tuple[str, int], TokenBucket] = {}
        self.shed_level = 0
        self._healthy_streak = 0
        self._last_eval = clock()
        self.transitions: list[dict] = []  # bounded history for /slo + tests
        # ---- Bastion per-tenant state (all bounded by max_tracked_tenants)
        self.tenant_weights = dict(tenant_weights or {})
        self.default_weight = float(default_weight)
        self.tenant_burn_threshold = float(tenant_burn_threshold)
        self.tenant_shed_hold = int(tenant_shed_hold)
        self.max_tracked_tenants = int(max_tracked_tenants)
        self._tenants: set[str] = set()
        # (tenant, class idx) -> [arrivals, bad outcomes] in the current
        # evaluation window; arrivals tick in decide(), bad in note_outcome
        self._window: dict[tuple[str, int], list] = {}
        # tenant -> {"level": shed classes, "streak": clean evals since}
        self._tenant_shed: dict[str, dict] = {}
        self.tenant_transitions: list[dict] = []
        # transition subscribers (event-driven waits for harnesses and
        # tests — the sleep-free alternative to polling `transitions`);
        # invoked synchronously at transition time, exceptions swallowed
        # so an observer can never wedge the decision loop
        self._subscribers: list = []

    @classmethod
    def from_config(cls, acfg, alerts=None, breakers=None,
                    clock: Callable[[], float] = time.monotonic,
                    tenancy=None) -> "AdmissionController":
        """Build from an AdmissionConfig-shaped object (duck-typed so this
        module never imports the config tree — the SloEngine.from_obs
        pattern). `tenancy` optionally supplies a TenancyConfig-shaped
        object for the Bastion weighted-fair / burn-shed knobs."""
        g = lambda name, dflt: getattr(acfg, name, dflt)  # noqa: E731
        t = lambda name, dflt: getattr(tenancy, name, dflt)  # noqa: E731
        rates = {
            "interactive": (g("interactive_rate", 400.0), g("interactive_burst", 800.0)),
            "aggregate": (g("aggregate_rate", 64.0), g("aggregate_burst", 128.0)),
            "background": (g("background_rate", 16.0), g("background_burst", 32.0)),
        }
        return cls(
            rates=rates,
            class_overrides=dict(g("classes", None) or {}),
            eval_interval=g("eval_interval", 1.0),
            shed_hold=g("shed_hold", 3),
            max_shed_level=g("max_shed_level", 2),
            breaker_shed_fraction=g("breaker_shed_fraction", 0.5),
            tenant_header=g("tenant_header", "x-dds-tenant"),
            alerts=alerts,
            breakers=breakers,
            clock=clock,
            tenant_weights=dict(t("weights", None) or {}),
            default_weight=t("default_weight", 1.0),
            tenant_burn_threshold=t("burn_threshold", 0.5),
            tenant_shed_hold=t("shed_hold", 3),
            max_tracked_tenants=t("max_tenants", 1024),
        )

    # ------------------------------------------------------------ decisions

    def route_class(self, route: str) -> int:
        return route_class(route, self.class_overrides)

    def _track(self, tenant: str) -> str:
        """Bounded tenant tracking: a tenant beyond `max_tracked_tenants`
        folds into the shared "overflow" identity for buckets, windows,
        and shed state (requests still serve; attribution coarsens)."""
        if tenant in self._tenants:
            return tenant
        if len(self._tenants) < self.max_tracked_tenants:
            self._tenants.add(tenant)
            return tenant
        return "overflow"

    def weight(self, tenant: str) -> float:
        return float(self.tenant_weights.get(tenant, self.default_weight))

    def _bucket(self, tenant: str, ci: int) -> TokenBucket | None:
        spec = self.rates.get(CLASSES[ci])
        if spec is None:
            return None
        key = (tenant, ci)
        b = self._buckets.get(key)
        if b is None:
            b = self._buckets[key] = TokenBucket(spec[0], spec[1], self._clock)
        return b

    def _shed_floor(self) -> int:
        """Lowest class index currently being shed (len(CLASSES) = none)."""
        return len(CLASSES) - self.shed_level

    def note_outcome(self, tenant: str, klass: str, good: bool) -> None:
        """Per-tenant burn attribution feed: the REST edge reports how
        each ADMITTED request actually ended (good = non-5xx within its
        latency objective). Bad outcomes accumulate against the tenant in
        the current evaluation window; `_evaluate_locked` uses the shares
        to decide whether distress is one tenant's or the fleet's."""
        ci = CLASSES.index(klass) if klass in CLASSES else len(CLASSES) - 1
        with self._lock:
            cell = self._window.setdefault((self._track(tenant), ci), [0, 0])
            if not good:
                cell[1] += 1

    def decide(self, route: str, tenant: str = "default") -> Decision:
        """Admit/reject one request. Called at the REST edge BEFORE a
        Deadline is minted, so every rejection costs microseconds, not a
        burned budget."""
        with self._lock:
            self._maybe_evaluate()
            ci = self.route_class(route)
            klass = CLASSES[ci]
            tenant = self._track(tenant)
            self._window.setdefault((tenant, ci), [0, 0])[0] += 1
            if ci >= self._shed_floor():
                metrics.inc("dds_admission_requests_total", outcome="shed",
                            help="admission verdicts by outcome and class",
                            **{"class": klass})
                return Decision(False, 503, self._shed_retry_after(),
                                f"shedding {klass} (level {self.shed_level})",
                                klass)
            tshed = self._tenant_shed.get(tenant)
            if tshed is not None and ci >= len(CLASSES) - tshed["level"]:
                metrics.inc("dds_admission_requests_total",
                            outcome="tenant_shed",
                            help="admission verdicts by outcome and class",
                            **{"class": klass})
                return Decision(
                    False, 429,
                    self.eval_interval * max(1, self.tenant_shed_hold),
                    f"tenant {tenant!r} shed (burn-driven)", klass)
            bucket = self._bucket(tenant, ci)
            if bucket is not None and not bucket.try_acquire():
                eta = bucket.refill_eta()
                metrics.inc("dds_admission_requests_total", outcome="throttled",
                            help="admission verdicts by outcome and class",
                            **{"class": klass})
                return Decision(False, 429, eta,
                                f"tenant {tenant!r} over {klass} rate", klass)
            metrics.inc("dds_admission_requests_total", outcome="admitted",
                        help="admission verdicts by outcome and class",
                        **{"class": klass})
            return Decision(True, 200, 0.0, "", klass)

    def _shed_retry_after(self) -> float:
        """When should a shed client come back? The nearest breaker
        half-open probe if the distress is breaker-shaped, else the
        soonest the ratchet could possibly step down."""
        _, etas = self._breakers()
        positive = [e for e in etas if e > 0]
        if positive:
            return min(positive)
        return self.eval_interval * max(1, self.shed_hold)

    # ----------------------------------------------------------- evaluation

    def _maybe_evaluate(self) -> None:
        if self._clock() - self._last_eval >= self.eval_interval:
            self._evaluate_locked()

    def evaluate(self) -> int:
        """One controller tick (the proxy runs this on a timer; decide()
        also ticks lazily under traffic). Returns the shed level."""
        with self._lock:
            self._evaluate_locked()
            return self.shed_level

    def _evaluate_locked(self) -> None:
        elapsed = max(1e-6, self._clock() - self._last_eval)
        self._last_eval = self._clock()
        alert_classes = {self.route_class(r) for r in self._alerts()}
        n_coord, open_etas = self._breakers()
        breaker_bad = (
            n_coord > 0
            and len(open_etas) >= max(1, math.ceil(self.breaker_shed_fraction * n_coord))
        )
        # only classes we are still SERVING count as distress: a shed
        # class burns its budget by construction (its 503s are ours), and
        # feeding that back would latch the ratchet at max forever
        serving_floor = self._shed_floor()
        slo_bad = any(ci < serving_floor for ci in alert_classes)
        window, self._window = self._window, {}
        self._rebalance_locked(window, elapsed)
        dominant = self._attribute_locked(window) if slo_bad else None
        self._step_tenants_locked(dominant)
        if dominant is not None and not breaker_bad:
            # one tenant owns the burn: it has just been shed above —
            # hold the FLEET ratchet where it is (the point of Bastion:
            # a distressed tenant sheds itself, not everyone)
            self._healthy_streak = 0
        elif breaker_bad or slo_bad:
            self._healthy_streak = 0
            if self.shed_level < self.max_shed_level:
                reason = "breakers" if breaker_bad else "slo_burn"
                self._transition(self.shed_level + 1, reason)
        else:
            self._healthy_streak += 1
            # hysteresis: one level at a time, and only after shed_hold
            # consecutive clean evaluations — recovery is gradual where
            # onset is immediate
            if self.shed_level > 0 and self._healthy_streak >= self.shed_hold:
                self._healthy_streak = 0
                self._transition(self.shed_level - 1, "recovered")
        metrics.set("dds_admission_shed_level", self.shed_level,
                    help="Bulwark shed level (0=none; higher sheds lower "
                         "priority classes first)")
        metrics.set("dds_admission_tenants_shed", len(self._tenant_shed),
                    help="tenants currently burn-shed by Bulwark")

    # ------------------------------------------------- Bastion tenant logic

    def _rebalance_locked(self, window: dict, elapsed: float) -> None:
        """Weighted-fair bucket refill: per class, when the window's
        aggregate arrival rate exceeds the class rate, each active
        tenant's bucket contracts to its weight share of the class rate;
        otherwise every bucket restores to the full class rate
        (work-conserving — fairness only costs anything under
        contention)."""
        for ci, klass in enumerate(CLASSES):
            spec = self.rates.get(klass)
            if spec is None:
                continue
            active = [t for (t, c), cell in window.items()
                      if c == ci and cell[0] > 0]
            demand = sum(window[(t, ci)][0] for t in active) / elapsed
            contended = len(active) > 1 and demand > spec[0]
            wsum = sum(self.weight(t) for t in active) or 1.0
            for (t, c), bucket in self._buckets.items():
                if c != ci:
                    continue
                if contended and t in active:
                    share = self.weight(t) / wsum
                    bucket.rate = max(1e-9, spec[0] * share)
                    bucket.burst = max(1.0, spec[1] * share)
                else:
                    bucket.rate, bucket.burst = spec[0], spec[1]

    def _attribute_locked(self, window: dict) -> str | None:
        """The tenant owning >= tenant_burn_threshold of the window's bad
        outcomes, or None when the burn is not attributable to one tenant
        (too little signal, or spread across tenants). The "default"
        tenant is never self-shed — in single-tenant deployments it IS
        the fleet, and the global ratchet already covers that."""
        bad: dict[str, int] = {}
        for (t, _c), cell in window.items():
            bad[t] = bad.get(t, 0) + cell[1]
        total = sum(bad.values())
        if total < 4:
            return None
        tenant, worst = max(bad.items(), key=lambda kv: kv[1])
        if tenant == "default" or worst / total < self.tenant_burn_threshold:
            return None
        return tenant

    def _step_tenants_locked(self, dominant: str | None) -> None:
        """Shed the dominant burning tenant; age out tenants whose burn
        stopped (tenant_shed_hold clean evaluations, same hysteresis as
        the global ratchet)."""
        if dominant is not None:
            state = self._tenant_shed.get(dominant)
            if state is None:
                self._tenant_shed[dominant] = {
                    "level": max(1, self.max_shed_level), "streak": 0,
                }
                self._tenant_transition(dominant, "shed", "tenant_burn")
            else:
                state["streak"] = 0
        for tenant in list(self._tenant_shed):
            if tenant == dominant:
                continue
            state = self._tenant_shed[tenant]
            state["streak"] += 1
            if state["streak"] >= self.tenant_shed_hold:
                del self._tenant_shed[tenant]
                self._tenant_transition(tenant, "unshed", "recovered")

    def _tenant_transition(self, tenant: str, direction: str,
                           reason: str) -> None:
        record = {"at": self._clock(), "tenant": tenant,
                  "direction": direction, "reason": reason}
        self.tenant_transitions.append(record)
        del self.tenant_transitions[:-64]
        tracer.event("admission.tenant_" + direction, tenant=tenant,
                     reason=reason)
        metrics.inc("dds_admission_tenant_transitions_total",
                    direction=direction,
                    help="Bulwark per-tenant burn-shed transitions")
        from dds_tpu.obs.flight import flight

        flight.record(f"admission_tenant_{direction}", tenant=tenant,
                      reason=reason)

    def shed_tenants(self) -> list[str]:
        """Tenants currently burn-shed (Helmsman's tenant-attribution
        signal rides on this plus SloEngine.tenant_burns)."""
        with self._lock:
            return sorted(self._tenant_shed)

    def subscribe(self, fn) -> None:
        """Register a transition observer: `fn(record)` fires on every
        shed/unshed transition (same dict shape as `transitions`
        entries). The event-driven hook the overload harnesses wait on
        instead of sleeping and polling. A NON-ZERO current level is
        delivered immediately on subscription, so a late subscriber
        (the Helmsman controller attaching mid-incident) sees the shed
        it joined into instead of waiting for the next transition."""
        self._subscribers.append(fn)
        if self.shed_level > 0:
            try:
                fn({
                    "at": self._clock(), "from": self.shed_level,
                    "to": self.shed_level, "direction": "shed",
                    "reason": "subscribed mid-shed",
                    "shedding": [CLASSES[i] for i in range(len(CLASSES))
                                 if i >= len(CLASSES) - self.shed_level],
                })
            except Exception:  # observers must never wedge the ratchet
                pass

    def unsubscribe(self, fn) -> None:
        try:
            self._subscribers.remove(fn)
        except ValueError:
            pass

    def _transition(self, level: int, reason: str) -> None:
        direction = "shed" if level > self.shed_level else "unshed"
        prev, self.shed_level = self.shed_level, level
        record = {
            "at": self._clock(), "from": prev, "to": level,
            "direction": direction, "reason": reason,
            "shedding": [CLASSES[i] for i in range(len(CLASSES))
                         if i >= len(CLASSES) - level],
        }
        self.transitions.append(record)
        del self.transitions[:-64]  # bounded history
        for fn in list(self._subscribers):
            try:
                fn(dict(record))
            except Exception:  # observers must never wedge the ratchet
                pass
        tracer.event("admission." + direction, level=level, reason=reason)
        metrics.inc("dds_admission_transitions_total", direction=direction,
                    reason=reason,
                    help="Bulwark shed-level transitions")
        # a shed-level change IS an incident-grade event either way:
        # post-mortems need to know when load shedding began and ended
        from dds_tpu.obs.flight import flight

        flight.record(f"admission_{direction}", level=level, prev=prev,
                      reason=reason, shedding=record["shedding"])

    # -------------------------------------------------------------- surface

    def report(self) -> dict:
        """Operator view (served under GET /slo): current level, what is
        being shed, and the recent transition history."""
        with self._lock:
            return {
                "shed_level": self.shed_level,
                "max_shed_level": self.max_shed_level,
                "shedding": [CLASSES[i] for i in range(len(CLASSES))
                             if i >= len(CLASSES) - self.shed_level],
                "healthy_streak": self._healthy_streak,
                "shed_hold": self.shed_hold,
                "transitions": list(self.transitions[-8:]),
                "tenants": {
                    "tracked": len(self._tenants),
                    "max_tracked": self.max_tracked_tenants,
                    "shed": sorted(self._tenant_shed),
                    "burn_threshold": self.tenant_burn_threshold,
                    "transitions": list(self.tenant_transitions[-8:]),
                },
            }


class AdaptiveCoalescer:
    """Sizes the fold-coalescing window from observed arrival rate.

    The proxy's coalescing window (ProxyConfig.coalesce_window) gathers
    concurrent sub-crossover folds into one segmented device dispatch. A
    fixed window is wrong at both ends: too short under load (batches
    dispatch half-full, dispatch overhead per fold stays high) and pure
    latency when sized for load but traffic is idle. This tracks a
    time-decayed EWMA of the fold arrival rate (`note_fold`, called per
    aggregate fold at the proxy) and answers `window()`:

        idle (expected co-arrivals ~ 0)  -> base window (snap small)
        loaded                           -> clamp(target_folds / rate,
                                                 base, max_window)

    so the window stretches exactly until ~`target_folds` arrivals are
    expected to share the dispatch, and no further — full, steady batch
    shapes, the property the HE-accelerator literature (BTS) gets its
    throughput from."""

    def __init__(self, base_window: float, max_window: float,
                 target_folds: float = 8.0, half_life: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        self.base_window = float(base_window)
        self.max_window = max(float(max_window), self.base_window)
        self.target_folds = float(target_folds)
        self.half_life = float(half_life)
        self._clock = clock
        self._lock = threading.Lock()
        self._ewma_rate = 0.0   # folds per second
        self._last: float | None = None
        self._folds = 0

    def note_fold(self, width: int = 1) -> None:
        """Record one fold arrival (the observed-load signal)."""
        with self._lock:
            self._folds += 1
            now = self._clock()
            if self._last is None:
                self._last = now
                return
            dt = max(1e-6, now - self._last)
            self._last = now
            # time-decayed EWMA: one arrival every dt seconds is an
            # instantaneous rate of 1/dt; weight by how much of the
            # half-life elapsed so bursts and lulls both converge fast
            alpha = 1.0 - math.exp(-dt / self.half_life)
            self._ewma_rate += alpha * ((1.0 / dt) - self._ewma_rate)

    def rate(self) -> float:
        """Current folds/s estimate, decayed for elapsed idle time (a
        burst an hour ago must not keep the window stretched)."""
        with self._lock:
            if self._last is None:
                return 0.0
            idle = max(0.0, self._clock() - self._last)
            return self._ewma_rate * math.exp(-idle / self.half_life)

    def window(self) -> float:
        r = self.rate()
        # fewer than one expected co-arrival even at the widest window:
        # waiting buys nothing — snap to the base window
        if r * self.max_window < 1.0:
            return self.base_window
        return min(self.max_window, max(self.base_window, self.target_folds / r))

    def stats(self) -> dict:
        return {
            "rate": round(self.rate(), 3),
            "window": round(self.window(), 6),
            "folds": self._folds,
        }
