"""ChaosNet: deterministic network-fault fabric over any `Transport`.

The reference system's dependability claims were only ever exercised on a
healthy network plus two injected process faults (crash / compromise).
ChaosNet wraps any transport (`InMemoryNet` for the test fabric, `TcpNet`
for a real deployment soak) and applies a SEEDED fault schedule per
(src, dest) link, so linearizability and recovery can be tested under
adversarial schedules and every run is reproducible from its seed:

- **drop**: the message never arrives;
- **delay** (fixed + uniform jitter): delivery is deferred off-loop;
- **duplicate**: the message arrives twice;
- **reorder**: the message is parked and overtaken by the link's next
  message (flushed on a timer so a quiet link cannot strand it);
- **corrupt**: the serialized payload gets a flipped byte — downstream the
  HMAC/codec layers must reject it (undecodable corruptions degrade to a
  drop, exactly like `TcpNet`'s frame-decode guard);
- **partition**: symmetric or asymmetric link cuts between endpoint
  groups, with optional timed heal.

Fault decisions are drawn from one seeded `random.Random` synchronously
inside `send()`, in call order, and appended to `trace` — the same seed
over the same send sequence reproduces the identical fault trace
(asserted in tests/test_chaos.py). Endpoints are matched by bare name
(`"host:port/replica-3"` -> `"replica-3"`), so one schedule works on both
transports.
"""

from __future__ import annotations

import asyncio
import logging
import random
from dataclasses import dataclass, field
from typing import Optional

from dds_tpu.core import messages as M
from dds_tpu.core.transport import Transport
from dds_tpu.obs.metrics import metrics
from dds_tpu.utils.tasks import supervised_task
from dds_tpu.utils.trace import tracer

log = logging.getLogger("dds.chaos")


@dataclass
class LinkFaults:
    """Fault rates/parameters for one link (or one destination)."""

    drop: float = 0.0        # P(message silently lost)
    delay: float = 0.0       # fixed delivery delay, seconds
    jitter: float = 0.0      # + U(0, jitter) seconds
    duplicate: float = 0.0   # P(delivered twice)
    reorder: float = 0.0     # P(parked until the link's next message passes)
    corrupt: float = 0.0     # P(one payload byte flipped)


def _name(addr: str) -> str:
    """Bare endpoint name, transport-agnostic ("h:p/replica-3" -> "replica-3")."""
    return addr.rsplit("/", 1)[-1]


@dataclass
class Partition:
    """An active cut between `a` and `b` (None = everyone else). Symmetric
    cuts both directions; asymmetric cuts only a -> b (one-way loss)."""

    a: frozenset
    b: Optional[frozenset] = None
    symmetric: bool = True
    healed: bool = False
    _fabric: object = field(default=None, repr=False)

    def blocks(self, src: str, dest: str) -> bool:
        if self.healed:
            return False
        s, d = _name(src), _name(dest)
        if self.b is None:
            cut = (s in self.a) != (d in self.a)
            if self.symmetric:
                return cut
            return cut and s in self.a
        fwd = s in self.a and d in self.b
        if self.symmetric:
            return fwd or (s in self.b and d in self.a)
        return fwd

    def heal(self) -> None:
        self.healed = True
        if self._fabric is not None:
            self._fabric._note("*", "*", "partition", "heal")


class ChaosNet(Transport):
    """Seeded fault-injection wrapper; registration passes straight through
    to the inner transport, only `send` is intercepted."""

    def __init__(self, inner: Transport, seed: int = 0):
        self.inner = inner
        self.seed = seed
        self._rng = random.Random(seed)
        self.default_faults = LinkFaults()
        # (src_name, dest_name) -> LinkFaults, or dest_name -> LinkFaults;
        # the pair key wins over the dest key, which wins over the default
        self.links: dict = {}
        # WAN topology: bare endpoint name -> region label, and
        # (src_region, dest_region) -> LinkFaults. Resolution order per
        # send is pair > dest > region-pair > default, so a surgical
        # per-link override still beats the blanket WAN matrix
        self.regions: dict[str, str] = {}
        self.region_links: dict = {}
        self.partitions: list[Partition] = []
        # (seq, src, dest, msg type, action) — the deterministic fault trace
        self.trace: list[tuple] = []
        self._seq = 0
        self._tasks: set = set()
        # (src, dest) -> parked (msg, flush handle) for reordering
        self._parked: dict = {}

    # -------------------------------------------------- Transport interface

    def register(self, addr, handler):
        self.inner.register(addr, handler)

    def unregister(self, addr):
        self.inner.unregister(addr)

    def has_endpoint(self, addr):
        return self.inner.has_endpoint(addr)

    @property
    def advertised(self) -> str:
        """Inner transport's peer-visible "host:port" (TcpNet); empty for
        fabrics without one — Meridian derives endpoint namers through the
        chaos wrap."""
        return getattr(self.inner, "advertised", "")

    def local_addr(self, name: str) -> str:
        fn = getattr(self.inner, "local_addr", None)
        return fn(name) if fn is not None else name

    # ------------------------------------------------------------- schedule

    def set_link(self, src: str, dest: str, faults: LinkFaults) -> None:
        """Fault the (src, dest) link, both named by bare endpoint name."""
        self.links[(src, dest)] = faults

    def set_dest(self, dest: str, faults: LinkFaults) -> None:
        """Fault every link INTO `dest` (bare endpoint name)."""
        self.links[dest] = faults

    def set_pair(self, a: str, b: str, faults: LinkFaults) -> None:
        """Fault both directions between two endpoints."""
        self.links[(a, b)] = faults
        self.links[(b, a)] = faults

    def set_regions(self, mapping: dict) -> None:
        """Assign endpoints (bare names) to named regions. Merges into the
        existing assignment so groups can be labeled incrementally."""
        self.regions.update({_name(k): v for k, v in mapping.items()})

    def region_of(self, addr: str) -> str:
        """The endpoint's region label ("" when unassigned)."""
        return self.regions.get(_name(addr), "")

    def set_region_link(self, src_region: str, dest_region: str,
                        faults: LinkFaults) -> None:
        """Fault every link from `src_region` into `dest_region`. One-way:
        call twice (or use geo.wan.apply_profile) for a symmetric WAN."""
        self.region_links[(src_region, dest_region)] = faults

    def region_members(self, region: str) -> list[str]:
        """Bare endpoint names currently assigned to `region`, sorted."""
        return sorted(n for n, r in self.regions.items() if r == region)

    def region_partition(
        self,
        region: str,
        symmetric: bool = True,
        duration: Optional[float] = None,
    ) -> Partition:
        """Cut an entire region off from the rest of the fleet — the
        region-death primitive. Asymmetric cuts only traffic LEAVING the
        region (its members still hear the world but cannot answer)."""
        members = self.region_members(region)
        if not members:
            raise ValueError(f"region {region!r} has no registered endpoints")
        return self.partition(members, symmetric=symmetric, duration=duration)

    def clear_faults(self) -> None:
        self.links.clear()
        self.region_links.clear()
        self.default_faults = LinkFaults()

    def partition(
        self,
        a,
        b=None,
        symmetric: bool = True,
        duration: Optional[float] = None,
    ) -> Partition:
        """Cut links between groups `a` and `b` (None = everyone else);
        returns the Partition, healable via `.heal()` or automatically
        after `duration` seconds."""
        p = Partition(
            frozenset(_name(x) for x in a),
            None if b is None else frozenset(_name(x) for x in b),
            symmetric,
            _fabric=self,
        )
        self.partitions.append(p)
        self._note("*", "*", "partition", f"cut a={sorted(p.a)}")
        if duration is not None:
            self._spawn(self._timed_heal(p, duration))
        return p

    def heal_all(self) -> None:
        """Lift every partition and clear all link faults."""
        for p in self.partitions:
            p.healed = True
        self.partitions.clear()
        self.clear_faults()
        self._note("*", "*", "heal", "all")

    async def _timed_heal(self, p: Partition, duration: float) -> None:
        await asyncio.sleep(duration)
        p.heal()

    # ----------------------------------------------------------------- send

    def _faults_for(self, src: str, dest: str) -> LinkFaults:
        s, d = _name(src), _name(dest)
        explicit = self.links.get((s, d)) or self.links.get(d)
        if explicit is not None:
            return explicit
        if self.region_links:
            rp = self.region_links.get(
                (self.regions.get(s, ""), self.regions.get(d, "")))
            if rp is not None:
                return rp
        return self.default_faults

    def _note(self, src: str, dest: str, kind: str, action: str) -> None:
        self.trace.append((self._seq, _name(src), _name(dest), kind, action))
        self._seq += 1
        # Telescope annotations: _note runs synchronously inside send(), so
        # the event lands on the REQUEST's trace (contextvar still set) —
        # a post-mortem sees exactly which quorum leg the fabric dropped or
        # delayed. The metric label is the action family only ("delay", not
        # "delay=0.0123"): label values must stay bounded.
        act = action.split("=", 1)[0]
        metrics.inc("dds_chaos_events_total", action=act,
                    help="ChaosNet fault injections by action")
        tracer.event("chaos." + act, src=_name(src), dest=_name(dest),
                     msg=kind, action=action)

    def send(self, src: str, dest: str, msg: object) -> None:
        # every fault decision happens HERE, synchronously in send-call
        # order, so the rng stream (and therefore the trace) is a pure
        # function of the seed and the send sequence
        kind = type(msg).__name__
        for p in self.partitions:
            if p.blocks(src, dest):
                self._note(src, dest, kind, "partition_drop")
                return
        f = self._faults_for(src, dest)
        rng = self._rng
        if f.drop and rng.random() < f.drop:
            self._note(src, dest, kind, "drop")
            return
        if f.corrupt and rng.random() < f.corrupt:
            msg = self._corrupt(msg)
            if msg is None:
                self._note(src, dest, kind, "corrupt_undecodable")
                return
            self._note(src, dest, kind, "corrupt")
        delay = f.delay + (rng.uniform(0.0, f.jitter) if f.jitter else 0.0)
        copies = 2 if f.duplicate and rng.random() < f.duplicate else 1
        if copies == 2:
            self._note(src, dest, kind, "duplicate")
        park = bool(f.reorder) and rng.random() < f.reorder

        # a parked predecessor on this link is released BEHIND this message
        link = (_name(src), _name(dest))
        parked = self._parked.pop(link, None)

        if park and parked is None:
            self._note(src, dest, kind, "parked")
            handle = self._spawn(self._flush_parked(link, delay + 0.05))
            self._parked[link] = (src, dest, msg, delay, copies, handle)
            return
        if delay > 0:
            self._note(src, dest, kind, f"delay={delay:.4f}")
        for _ in range(copies):
            self._dispatch(src, dest, msg, delay)
        if parked is not None:
            psrc, pdest, pmsg, pdelay, pcopies, phandle = parked
            phandle.cancel()
            self._note(psrc, pdest, type(pmsg).__name__, "released_reordered")
            for _ in range(pcopies):
                self._dispatch(psrc, pdest, pmsg, pdelay)

    def _dispatch(self, src: str, dest: str, msg: object, delay: float) -> None:
        if delay > 0:
            self._spawn(self._deliver_later(src, dest, msg, delay))
        else:
            self.inner.send(src, dest, msg)

    async def _deliver_later(self, src, dest, msg, delay) -> None:
        await asyncio.sleep(delay)
        self.inner.send(src, dest, msg)

    async def _flush_parked(self, link, after: float) -> None:
        """A quiet link must not strand a parked message forever."""
        await asyncio.sleep(after)
        parked = self._parked.pop(link, None)
        if parked is not None:
            src, dest, msg, delay, copies, _ = parked
            for _ in range(copies):
                self._dispatch(src, dest, msg, delay)

    def _corrupt(self, msg):
        """Flip one byte of the canonical serialization. A still-decodable
        mutation reaches the receiver (whose MAC layer must reject it); an
        undecodable one degrades to a drop, like TcpNet's codec guard."""
        try:
            raw = bytearray(M.dumps(msg))
        except Exception:
            return None
        raw[self._rng.randrange(len(raw))] ^= 0x20
        try:
            return M.loads(bytes(raw))
        except Exception:
            return None

    def _spawn(self, coro) -> asyncio.Task:
        task = supervised_task(coro, name="chaos.delivery")
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    # ------------------------------------------------------------ lifecycle

    async def quiesce(self) -> None:
        """Drain chaos-deferred deliveries, then the inner transport's
        in-flight work (and any follow-ups they spawned)."""
        while True:
            pending = [t for t in self._tasks if not t.done()]
            if not pending and not self._parked:
                break
            for link in list(self._parked):
                parked = self._parked.pop(link, None)
                if parked is not None:
                    src, dest, msg, delay, copies, handle = parked
                    handle.cancel()
                    for _ in range(copies):
                        self._dispatch(src, dest, msg, delay)
            if pending:
                await asyncio.gather(*pending, return_exceptions=True)
            await asyncio.sleep(0)
        inner_quiesce = getattr(self.inner, "quiesce", None)
        if inner_quiesce is not None:
            await inner_quiesce()

    async def start(self) -> None:
        start = getattr(self.inner, "start", None)
        if start is not None:
            await start()

    async def stop(self) -> None:
        """Cancel chaos-deferred deliveries. The INNER transport is left to
        its own owner (launch() tracks it as a separate stoppable; wrapping
        must not double-stop it)."""
        for t in list(self._tasks):
            t.cancel()
        for t in list(self._tasks):
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._parked.clear()
