"""Merkle anti-entropy: background convergence without client reads.

The ABD protocol repairs stale replicas lazily — a read's write-back phase
touches exactly the keys clients happen to read. A healed partition, a
snapshot-restored rejoiner, or a replica whose verified reseed rejected
forged entries (core/replica._try_complete_recovery) therefore stays
divergent for every key no client asks about. This module closes that gap:

- `MerkleIndex`: an incremental two-level hash tree over the repository's
  tracked entries (key -> tag, value-digest). Leaf buckets are XOR
  accumulators of per-entry digests (order-independent, O(1) update per
  store); the root hashes the bucket vector. Implicit defaults minted by
  `_state()` (tag seq 0, value None) are excluded — they differ per
  replica by tag id and would read as fake divergence.

- `AntiEntropy`: one instance per replica (created in BFTABDNode.__init__)
  that both ANSWERS peers' sync phases (root -> buckets -> keys -> repair,
  delegated from the replica's behavior handlers) and, when started, runs
  a jittered background loop pulling from one random peer per round:
  compare roots; on divergence fetch bucket vectors, walk divergent
  buckets' key listings, and repair stale keys via per-key signed value
  transfer — each repaired entry carries the standard ABD HMAC over
  (value, tag, nonce) and is installed store-if-newer, the same
  authenticity and monotonicity bar as a protocol `Write` write-back.

Sync is pull-based and one-directional per round: keys where the PEER is
stale are left for the peer's own loop (every replica runs one), keeping
rounds idempotent and free of write amplification. Replies are HMAC-signed
(utils/sigs.antientropy_signature); a tag-equal-but-digest-divergent entry
is cryptographic evidence of a forged or corrupted value under a real tag
and is flight-recorded, never auto-overwritten (the tag order cannot say
which side is right — the audit/repair story for that class lives in the
proxy's cache audit and operator hands).
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import random
import time
from typing import Optional

from dds_tpu.core import messages as M
from dds_tpu.obs.flight import flight
from dds_tpu.obs.metrics import metrics
from dds_tpu.utils import sigs
from dds_tpu.utils.tasks import supervised_task
from dds_tpu.utils.trace import tracer

log = logging.getLogger("dds.antientropy")


class MerkleIndex:
    """Incremental hash index over (key -> tag, value-digest).

    Two levels: BUCKETS XOR-accumulator leaves (bucket = first byte of
    sha256(key) mod BUCKETS) and a root hash over the bucket vector.
    XOR makes updates O(1) and order-independent; forged-vector attacks
    on XOR malleability are out of scope because bucket vectors only
    travel inside HMAC-signed replies from peers that hold the intranet
    secret anyway (the ABD threat model, SURVEY.md §7).
    """

    BUCKETS = 64

    def __init__(self):
        self._acc = [0] * self.BUCKETS
        # key -> (tag, value-digest hex, contribution int)
        self._entries: dict[str, tuple] = {}

    @staticmethod
    def _tracked(tag, value) -> bool:
        # the `_state()` implicit default is (seq 0, None) with a per-
        # replica tag id; deletes are None under seq > 0 and ARE tracked
        return not (tag.seq == 0 and value is None)

    @classmethod
    def bucket_of(cls, key: str) -> int:
        return hashlib.sha256(key.encode()).digest()[0] % cls.BUCKETS

    @staticmethod
    def _contribution(key: str, tag, vd: str) -> int:
        blob = f"{key}|{tag.seq}|{tag.id}|{vd}".encode()
        return int.from_bytes(hashlib.sha256(blob).digest(), "big")

    def update(self, key: str, tag, value) -> None:
        old = self._entries.get(key)
        b = self.bucket_of(key)
        if old is not None:
            self._acc[b] ^= old[2]
            del self._entries[key]
        if self._tracked(tag, value):
            vd = sigs.value_digest(value)
            contrib = self._contribution(key, tag, vd)
            self._acc[b] ^= contrib
            self._entries[key] = (tag, vd, contrib)

    def rebuild(self, repository: dict) -> None:
        self._acc = [0] * self.BUCKETS
        self._entries = {}
        for key, (tag, value) in repository.items():
            self.update(key, tag, value)

    def root(self) -> str:
        return hashlib.sha256(
            b"".join(a.to_bytes(32, "big") for a in self._acc)
        ).hexdigest()

    def bucket_digests(self) -> list[str]:
        return [format(a, "064x") for a in self._acc]

    def entries_in(self, buckets) -> dict:
        """{key: [seq, id, value-digest]} for the given bucket ids."""
        wanted = {int(b) for b in buckets}
        return {
            k: [t.seq, t.id, vd]
            for k, (t, vd, _) in self._entries.items()
            if self.bucket_of(k) in wanted
        }

    def manifest(self) -> dict:
        """The full {key: [seq, id, value-digest]} attestation — what a
        replica signs into a StateDigest for verified state transfer."""
        return {k: [t.seq, t.id, vd] for k, (t, vd, _) in self._entries.items()}

    def get(self, key: str):
        """(tag, value-digest) for a tracked key, else None."""
        e = self._entries.get(key)
        return None if e is None else (e[0], e[1])

    def __len__(self) -> int:
        return len(self._entries)


class AntiEntropy:
    """Per-replica sync agent: answers peers' phases, runs the pull loop."""

    REPAIR_BATCH = 256  # keys per RepairRequest, bounding reply frames

    def __init__(self, node):
        self.node = node
        self.interval = 5.0
        self.jitter = 2.0
        self.sync_timeout = 2.0
        self._rng = random.Random()
        self._task: Optional[asyncio.Task] = None
        self._pending: dict[int, asyncio.Future] = {}
        # Atlas cross-region pairing: endpoint -> region labels, a bias
        # toward cross-region pulls (the links where divergence actually
        # accumulates after a WAN partition), and extra de-synchronising
        # jitter ahead of a cross-region round so a whole region's loops
        # never dogpile one WAN link at once
        self.regions: dict = {}
        self.cross_region_bias = 0.5
        self.cross_jitter = 0.0
        # observability surface, exported via /health + scrape-time gauges
        self.rounds = 0
        self.cross_rounds = 0
        self.repaired_total = 0
        self.last_divergence = 0   # divergent buckets seen in the last round
        self.last_sync: float | None = None  # monotonic ts of last completed round

    def configure(self, interval: float | None = None,
                  jitter: float | None = None,
                  sync_timeout: float | None = None,
                  rng: random.Random | None = None,
                  regions: dict | None = None,
                  cross_region_bias: float | None = None,
                  cross_jitter: float | None = None) -> None:
        if interval is not None:
            self.interval = interval
        if jitter is not None:
            self.jitter = jitter
        if sync_timeout is not None:
            self.sync_timeout = sync_timeout
        if rng is not None:
            self._rng = rng
        if regions is not None:
            self.regions = dict(regions)
        if cross_region_bias is not None:
            self.cross_region_bias = cross_region_bias
        if cross_jitter is not None:
            self.cross_jitter = cross_jitter

    # -------------------------------------------------------- peer selection

    def _region_of(self, endpoint: str) -> str:
        return self.regions.get(
            endpoint, self.regions.get(endpoint.rsplit("/", 1)[-1], ""))

    def _pick_peer(self, peers: list[str]) -> tuple[str, bool]:
        """(peer, is_cross_region). Geo-unaware fabrics draw uniformly;
        geo-aware ones split peers by region and pull cross-region with
        probability `cross_region_bias` — all draws come from the one
        seeded rng, so a seeded fleet pairs identically every run."""
        my_region = self._region_of(self.node.addr)
        if not self.regions or not my_region:
            return self._rng.choice(peers), False
        local = [p for p in peers if self._region_of(p) == my_region]
        remote = [p for p in peers if self._region_of(p) != my_region]
        if remote and (not local
                       or self._rng.random() < self.cross_region_bias):
            return self._rng.choice(remote), True
        return self._rng.choice(local or peers), False

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self._task is None:
            self._task = supervised_task(self._loop(),
                                         name="antientropy.loop")

    def cancel(self) -> None:
        """Synchronous teardown for replaced nodes (redeploy rebuilds)."""
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def stop(self) -> None:
        if self._task is not None:
            task, self._task = self._task, None
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval + self._rng.uniform(0, self.jitter))
            peers = [p for p in self.node.all_replicas if p != self.node.addr]
            if not peers:
                continue
            peer, cross = self._pick_peer(peers)
            if cross:
                self.cross_rounds += 1
                if self.cross_jitter > 0:
                    await asyncio.sleep(self._rng.uniform(0, self.cross_jitter))
            try:
                await self.sync_once(peer)
            except asyncio.TimeoutError:
                metrics.inc(
                    "dds_antientropy_timeouts_total",
                    replica=self.node.name,
                    help="anti-entropy rounds abandoned on a silent peer",
                )
            except Exception:
                log.exception("anti-entropy round failed at %s", self.node.name)

    # ----------------------------------------------------------- initiator

    async def _ask(self, peer: str, msg) -> object:
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[msg.nonce] = fut
        try:
            self.node.net.send(self.node.addr, peer, msg)
            return await asyncio.wait_for(fut, self.sync_timeout)
        finally:
            self._pending.pop(msg.nonce, None)

    async def sync_once(self, peer: str) -> int:
        """One pull round against `peer`; returns the number of repaired
        keys. Raises asyncio.TimeoutError if the peer stays silent."""
        node = self.node
        secret = node.cfg.abd_mac_secret
        repaired = 0
        with tracer.span("antientropy.sync", replica=node.name,
                         peer=peer.rsplit("/", 1)[-1]) as meta:
            root_reply = await self._ask(
                peer, M.MerkleRootRequest(sigs.generate_nonce()))
            if not (isinstance(root_reply, M.MerkleRoot)
                    and sigs.validate_antientropy_signature(
                        secret, "root", [root_reply.root, root_reply.count],
                        root_reply.nonce, root_reply.signature)):
                meta["outcome"] = "bad_root_reply"
                return 0
            if root_reply.root == node.merkle.root():
                self.last_divergence = 0
                self._mark_round(meta, "in_sync", 0)
                return 0

            buckets_reply = await self._ask(
                peer, M.MerkleBucketRequest(sigs.generate_nonce()))
            if not (isinstance(buckets_reply, M.MerkleBuckets)
                    and sigs.validate_antientropy_signature(
                        secret, "buckets", list(buckets_reply.digests),
                        buckets_reply.nonce, buckets_reply.signature)):
                meta["outcome"] = "bad_buckets_reply"
                return 0
            mine = node.merkle.bucket_digests()
            divergent = [
                i for i, (a, b) in enumerate(zip(mine, buckets_reply.digests))
                if a != b
            ]
            self.last_divergence = len(divergent)
            if not divergent:
                self._mark_round(meta, "in_sync", 0)
                return 0

            keys_reply = await self._ask(
                peer, M.MerkleKeysRequest(list(divergent), sigs.generate_nonce()))
            if not (isinstance(keys_reply, M.MerkleKeys)
                    and sigs.validate_antientropy_signature(
                        secret, "keys", keys_reply.entries,
                        keys_reply.nonce, keys_reply.signature)):
                meta["outcome"] = "bad_keys_reply"
                return 0

            # key -> the peer's ADVERTISED (seq, id): kept so each repaired
            # entry can be audited against what the peer claimed to hold
            # (Watchtower's repair_convergence invariant — a peer that
            # advertises fresh but serves stale never converges)
            stale: dict[str, tuple] = {}
            for key, ent in keys_reply.entries.items():
                seq, tid, vd = int(ent[0]), str(ent[1]), str(ent[2])
                local = node.merkle.get(key)
                if local is None or (local[0].seq, local[0].id) < (seq, tid):
                    stale[key] = (seq, tid)
                elif (local[0].seq, local[0].id) == (seq, tid) and local[1] != vd:
                    # same tag, different value: one side holds a forged or
                    # corrupted value under a real tag — evidence, not a
                    # repair candidate (tag order cannot arbitrate it)
                    tracer.event("antientropy.digest_mismatch",
                                 replica=node.name, peer=peer, key=key)
                    metrics.inc(
                        "dds_antientropy_digest_mismatches_total",
                        replica=node.name,
                        help="tag-equal value-digest conflicts seen in sync",
                    )
                    await flight.record_async(
                        "antientropy_digest_mismatch",
                        replica=node.name, peer=peer, key=key,
                        local=[local[0].seq, local[0].id, local[1]],
                        remote=[seq, tid, vd],
                    )

            stale_keys = list(stale)
            for i in range(0, len(stale_keys), self.REPAIR_BATCH):
                batch = stale_keys[i:i + self.REPAIR_BATCH]
                nonce = sigs.generate_nonce()
                repair = await self._ask(peer, M.RepairRequest(batch, nonce))
                if not isinstance(repair, M.RepairReply):
                    continue
                wanted = set(batch)
                for key, e in repair.entries.items():
                    if key not in wanted:
                        continue
                    try:
                        tag = M.ABDTag(int(e["tag"][0]), str(e["tag"][1]))
                        value = e["value"]
                        sig = bytes.fromhex(e["sig"])
                    except (KeyError, TypeError, ValueError, IndexError):
                        continue
                    if not sigs.validate_abd_signature(
                            secret, value, tag, nonce, sig):
                        metrics.inc(
                            "dds_antientropy_rejected_repairs_total",
                            replica=node.name,
                            help="repair entries failing the ABD HMAC",
                        )
                        continue
                    cur = node.repository.get(key)
                    if cur is None or cur[0] < tag:
                        node._store(key, tag, value)
                        repaired += 1
                        src = stale[key]
                        # audit feed: installed vs advertised tag, checked
                        # by Watchtower's repair_convergence invariant
                        tracer.event(
                            "audit.repair", replica=node.name,
                            peer=peer.rsplit("/", 1)[-1], key=key,
                            src_seq=src[0], src_id=src[1],
                            seq=tag.seq, tag_id=tag.id,
                        )
            if repaired:
                metrics.inc(
                    "dds_antientropy_repaired_keys_total", repaired,
                    replica=node.name,
                    help="stale keys repaired by anti-entropy",
                )
            self._mark_round(meta, "repaired", repaired)
            return repaired

    def _mark_round(self, meta: dict, outcome: str, repaired: int) -> None:
        self.rounds += 1
        self.repaired_total += repaired
        self.last_sync = time.monotonic()
        meta["outcome"] = outcome
        meta["repaired"] = repaired
        meta["divergent_buckets"] = self.last_divergence
        metrics.inc(
            "dds_antientropy_rounds_total", replica=self.node.name,
            help="completed anti-entropy rounds",
        )

    # ------------------------------------------------------------ responder

    def handle(self, sender: str, msg) -> bool:
        """Dispatch one anti-entropy message (both roles); True = consumed.
        Called from the replica's behavior handlers, so a byzantine node
        simply never reaches here (omission, like the reference's)."""
        node = self.node
        secret = node.cfg.abd_mac_secret
        match msg:
            case M.MerkleRootRequest(nonce):
                root = node.merkle.root()
                count = len(node.merkle)
                sig = sigs.antientropy_signature(
                    secret, "root", [root, count], nonce)
                node._send(sender, M.MerkleRoot(root, count, nonce, sig))
            case M.MerkleBucketRequest(nonce):
                digests = node.merkle.bucket_digests()
                sig = sigs.antientropy_signature(
                    secret, "buckets", digests, nonce)
                node._send(sender, M.MerkleBuckets(digests, nonce, sig))
            case M.MerkleKeysRequest(buckets, nonce):
                entries = node.merkle.entries_in(buckets)
                sig = sigs.antientropy_signature(secret, "keys", entries, nonce)
                node._send(sender, M.MerkleKeys(entries, nonce, sig))
            case M.RepairRequest(keys, nonce):
                entries = {}
                for key in list(keys)[: self.REPAIR_BATCH]:
                    stored = node.repository.get(key)
                    if stored is None or not MerkleIndex._tracked(*stored):
                        continue
                    tag, value = stored
                    entries[key] = {
                        "tag": [tag.seq, tag.id],
                        "value": value,
                        "sig": sigs.abd_signature(
                            secret, value, tag, nonce).hex(),
                    }
                node._send(sender, M.RepairReply(entries, nonce))
            case (M.MerkleRoot() | M.MerkleBuckets() | M.MerkleKeys()
                  | M.RepairReply()):
                fut = self._pending.get(msg.nonce)
                if fut is not None and not fut.done():
                    fut.set_result(msg)
            case _:
                return False
        return True

    def stats(self) -> dict:
        """Health/scrape surface (http/server._sample_state_gauges)."""
        age = (
            None if self.last_sync is None
            else max(0.0, time.monotonic() - self.last_sync)
        )
        return {
            "rounds": self.rounds,
            "cross_region_rounds": self.cross_rounds,
            "repaired_keys": self.repaired_total,
            "divergent_buckets": self.last_divergence,
            "last_sync_age": age,
            "running": self._task is not None,
        }
