"""Tier-2 replicated core: BFT-ABD quorum protocol over async transports."""

from dds_tpu.core.messages import (  # noqa: F401
    ABDTag,
    Envelope,
    IRead,
    IWrite,
    IReadReply,
    IWriteReply,
)
from dds_tpu.core.replica import BFTABDNode, ReplicaConfig  # noqa: F401
from dds_tpu.core.transport import InMemoryNet  # noqa: F401
