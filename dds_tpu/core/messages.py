"""Wire messages for the BFT-ABD protocol, supervisor, and proxy contract.

Counterpart of the reference's three API files (`dds/api/ABDAPI.scala`,
`InternalAPI.scala`, `SupervisorAPI.scala`) and the small data models under
`dds/core/models/`. Serialization is tagged canonical JSON (language-neutral)
instead of Java/Akka serialization.

A "set" (the stored value) is a plain JSON list or None; tags order writes.
Tag ordering deviation (documented per SURVEY.md §7): the reference breaks
seq ties arbitrarily (`BFTABDNode.scala:185-188`); we order by (seq, id) —
the standard ABD total order — so write-back is deterministic.
"""

from __future__ import annotations

import base64
import json
from dataclasses import dataclass, fields
from typing import Any, Optional

DDSSet = list  # a stored record: JSON-safe list of column values


@dataclass(frozen=True, order=True)
class ABDTag:
    seq: int
    id: str


# --------------------------------------------------------------------------
# proxy <-> replica intermediate API (InternalAPI.scala)
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class IRead:
    key: str


@dataclass(frozen=True)
class IWrite:
    key: str
    set: Optional[DDSSet]


@dataclass(frozen=True)
class IReadReply:
    key: str
    set: Optional[DDSSet]
    # tag of the returned value (the write-back tag). Lets the proxy keep a
    # tag-validated aggregate cache. Covered by the proxy HMAC (tags are
    # predictable, so an unsigned tag could be swapped in transit). Cache
    # VALIDATION does not trust this field or its (single, possibly
    # Byzantine) coordinator at all — freshness comes from the proxy's own
    # quorum tag broadcast (AbdClient.read_tags), which a minority can only
    # inflate (spurious re-fetch), never deflate (stale serve); forged
    # VALUES from a Byzantine coordinator are bounded by the cache audit
    # (see http/server.py cache notes).
    tag: Optional[ABDTag] = None


@dataclass(frozen=True)
class IWriteReply:
    key: str
    tag: Optional[ABDTag] = None  # the tag the coordinator wrote (see above)


@dataclass(frozen=True)
class Envelope:
    call: Any          # one of the I* messages above
    nonce: int
    signature: bytes
    # Constellation shard-map epoch the SENDER routed under (-1 =
    # unsharded). Fenced at the replica: a group that does not own the
    # key under ITS current map answers WrongShard instead of serving, so
    # a stale map can never silently misroute an op during a reshard.
    epoch: int = -1


# --------------------------------------------------------------------------
# replica <-> replica ABD protocol (ABDAPI.scala)
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class ReadTag:
    key: str
    nonce: int


@dataclass(frozen=True)
class TagReply:
    tag: ABDTag
    key: str
    value: Optional[DDSSet]
    signature: bytes
    nonce: int


@dataclass(frozen=True)
class Write:
    tag: ABDTag
    key: str
    value: Optional[DDSSet]
    signature: bytes
    nonce: int


@dataclass(frozen=True)
class WriteAck:
    key: str
    nonce: int


@dataclass(frozen=True)
class Read:
    key: str
    nonce: int


@dataclass(frozen=True)
class ReadTagBatch:
    """Tag-phase-only quorum read over many keys at once (no Write phase
    follows), broadcast by the PROXY itself (AbdClient.read_tags) so no
    single coordinator can deflate the max. Replies carry tags, never
    contents. `signature` is the proxy MAC over (keys-digest, nonce):
    replicas answer (and burn an anti-replay nonce) only for holders of
    the proxy secret. This is the aggregate-cache validation op the
    reference lacks — it re-reads every stored set through full ABD
    quorums per aggregate instead (`dds/http/DDSRestServer.scala:397-446`)."""

    keys: tuple
    nonce: int
    signature: bytes = b""
    # sha256 fingerprint of the proxy's cached tag vector for `keys` (in
    # request order). A replica whose own vector fingerprints identically
    # answers with a tiny `unchanged` reply instead of re-serializing and
    # MACing all K tags — the steady-state fast path that keeps aggregate
    # freshness validation O(1) per side when nothing was written.
    fingerprint: Optional[bytes] = None
    # shard-map epoch, same fencing contract as Envelope.epoch
    epoch: int = -1


@dataclass(frozen=True)
class TagBatchReply:
    tags: tuple   # ABDTag per key in the request's order (empty if unchanged)
    digest: str
    signature: bytes
    nonce: int
    # unchanged=True: "my tag vector fingerprints to `fingerprint`, which
    # equals the one you sent" — signature then covers (fingerprint, digest,
    # nonce) via abd_batch_unchanged_signature. A full reply (unchanged=
    # False) also carries the replica's fingerprint so the proxy can adopt
    # it for its next request.
    unchanged: bool = False
    fingerprint: Optional[bytes] = None


@dataclass(frozen=True)
class ReadReply:
    tag: ABDTag
    key: str
    value: Optional[DDSSet]
    signature: bytes
    nonce: int


# --------------------------------------------------------------------------
# supervisor protocol (SupervisorAPI.scala)
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class Suspect:
    replica: str       # endpoint of the suspected replica
    nonce: int


@dataclass(frozen=True)
class Awake:
    pass


@dataclass(frozen=True)
class State:
    data: dict         # key -> {"tag": [seq, id], "value": set|None}
    nonces: list[int]


@dataclass(frozen=True)
class Sleep:
    data: dict
    nonces: list[int]


@dataclass(frozen=True)
class Complying:
    pass


@dataclass(frozen=True)
class Kill:
    """Control message: hard-restart the replica with empty state.

    The reference uses Akka `Kill` + the guardian's restart strategy
    (`BFTSupervisor.scala:115`, `BFTSupervisorStrategy.scala:8-10`); our
    transport delivers an explicit control message the node host honors.
    """


@dataclass(frozen=True)
class Redeploy:
    """Supervisor -> node-host agent: rebuild a fresh replica at `endpoint`
    (the host owning it re-instantiates and re-registers the node). The
    TCP analogue of the reference's remote actor deployment on a dead
    host (`BFTSupervisor.scala:130-149`, RemoteScope). Authentication is
    the transport's (frame MAC / mutual TLS / node signatures), the same
    trust the in-protocol Kill/Sleep control messages ride."""

    endpoint: str


@dataclass(frozen=True)
class Redeployed:
    """Node-host agent -> supervisor: the Redeploy target is registered
    (freshly rebuilt, or found already alive — idempotent success)."""

    endpoint: str


@dataclass(frozen=True)
class RequestReplicas:
    pass


@dataclass(frozen=True)
class ActiveReplicas:
    replicas: list[str]


# --------------------------------------------------------------------------
# Aegis recovery plane: verified state transfer + Merkle anti-entropy
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class StateDigestRequest:
    """Supervisor -> replica (or spare): send your signed state manifest.
    Answered by healthy AND sentinent nodes — the supervisor cross-checks a
    quorum of manifests before any recovery seeding, and ranks spares by
    manifest freshness."""

    nonce: int


@dataclass(frozen=True)
class StateDigest:
    """Replica -> supervisor: manifest = {key: [tag.seq, tag.id,
    value-digest]} over every tracked repository entry, HMAC-signed with
    the signer address bound in (utils/sigs.manifest_signature)."""

    manifest: dict
    nonce: int
    signature: bytes


@dataclass(frozen=True)
class SleepBegin:
    """Supervisor -> recovering node: verified-reseed header. `digests` is
    the collected quorum of manifests, each `[signer, manifest, nonce,
    signature-hex]`; the node re-verifies every HMAC and accepts a seeded
    entry only when its (tag, value-digest) is attested by at least
    `support` (= f+1) distinct signers. `total` StateChunk frames follow
    (any order — transports reorder)."""

    digests: list
    session: int
    total: int
    support: int
    nonces: list


@dataclass(frozen=True)
class StateChunk:
    """One slice of the seeding state: {key: {"tag": [seq, id], "value":
    set|None}}. Chunked so a large repository streams as bounded frames
    instead of one giant Sleep (TcpNet.MAX_FRAME)."""

    session: int
    seq: int
    entries: dict
    # which ingest path owns the session: "recovery" (SleepBegin reseed,
    # replaces the repository) or "migrate" (ShardMigrateBegin, merges
    # verified entries store-if-newer). Typed so a chunk that races its
    # header can never complete the WRONG kind of session.
    kind: str = "recovery"


@dataclass(frozen=True)
class MerkleRootRequest:
    nonce: int


@dataclass(frozen=True)
class MerkleRoot:
    """Anti-entropy phase 1 reply: root hash over the replica's (key ->
    tag, value-digest) index + tracked-entry count, HMAC-signed."""

    root: str
    count: int
    nonce: int
    signature: bytes


@dataclass(frozen=True)
class MerkleBucketRequest:
    nonce: int


@dataclass(frozen=True)
class MerkleBuckets:
    """Phase 2 reply: the per-bucket digest vector (hex per bucket)."""

    digests: list
    nonce: int
    signature: bytes


@dataclass(frozen=True)
class MerkleKeysRequest:
    buckets: list
    nonce: int


@dataclass(frozen=True)
class MerkleKeys:
    """Phase 3 reply: {key: [seq, id, value-digest]} for the requested
    divergent buckets — tags + digests only, values never travel here."""

    entries: dict
    nonce: int
    signature: bytes


@dataclass(frozen=True)
class RepairRequest:
    keys: list
    nonce: int


@dataclass(frozen=True)
class RepairReply:
    """Phase 4 reply: {key: {"tag": [seq, id], "value": set|None, "sig":
    hex}} where each sig is the standard ABD HMAC over (value, tag,
    nonce) — the same authenticity bar as a protocol Write, validated
    before store-if-newer."""

    entries: dict
    nonce: int


# --------------------------------------------------------------------------
# Constellation sharding plane (dds_tpu/shard)
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class WrongShard:
    """Replica -> proxy: epoch fence rejection. The addressed group does
    not own `key` under the replica's current shard map (epoch `epoch`).
    `nonce` correlates: the challenge nonce for an Envelope op, the
    request nonce for a ReadTagBatch. Signed with the proxy MAC over
    (key, nonce, ["wrong-shard", epoch]) so an in-path attacker cannot
    forge fence storms that stall the router with fake refreshes."""

    key: str
    epoch: int
    nonce: int
    signature: bytes


@dataclass(frozen=True)
class ShardMigrateBegin:
    """Rebalancer -> new-group replica: verified shard-migration header.
    Same attestation frame as SleepBegin — `digests` is a quorum of
    HMAC-signed state manifests from the SOURCE group, `support` the
    distinct-signer threshold (>= f+1) — but the receiver MERGES attested
    entries store-if-newer instead of replacing its repository, stays in
    its current behavior, and only accepts entries its own shard map says
    it owns at `epoch`. `total` StateChunk(kind="migrate") frames follow."""

    digests: list
    session: int
    total: int
    support: int
    epoch: int


@dataclass(frozen=True)
class ShardMigrateAck:
    """New-group replica -> rebalancer: migration session result.
    `accepted` counts entries installed (or already held at >= the
    attested tag); `rejected` counts entries that failed the digest
    quorum or fell outside the replica's owned keyspace."""

    session: int
    accepted: int
    rejected: int


# --------------------------------------------------------------------------
# Meridian multi-host fabric control plane (dds_tpu/fabric)
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class ShardMapInstall:
    """Controller -> group fabric agent: install `map` (ShardMap wire
    dict) into the group's FENCING state — the cross-host freeze step of
    a live reshard. The map is HMAC-signed with the intranet secret and
    re-verified by the receiving agent, so the frame only has to be
    delivered, not trusted; `force` permits the abort path's epoch
    rollback. `lease` > 0 installs the map provisionally for that many
    seconds (shard/shardmap.ShardState fence lease): if the reshard
    driver dies before committing, the group heals back to its last
    committed map instead of staying fenced forever. Rides the
    authenticated transport like the Kill/Redeploy control messages."""

    map: dict
    force: bool
    nonce: int
    lease: float = 0.0


@dataclass(frozen=True)
class ShardMapActivate:
    """Controller -> group fabric agent: adopt `map` as the ACTIVE
    routing map this process serves at GET /shards (and fences under,
    epoch-forward). Broadcast to every group after a reshard activates so
    remote long-pollers see the bump at their next gossip wake."""

    map: dict
    nonce: int


@dataclass(frozen=True)
class ShardMapAck:
    """Agent -> controller: install/activate outcome. `epoch` is the
    agent's fencing epoch after the attempt; ok=False carries the reason
    (bad signature, backwards epoch) so the rebalancer can abort."""

    nonce: int
    epoch: int
    ok: bool
    error: str = ""


@dataclass(frozen=True)
class ShardExportRequest:
    """Controller -> agent: export replica `endpoint`'s repository as
    migration seed DATA (one ShardExport frame; receivers re-verify every
    entry against the attested manifest quorum, so this is bandwidth, not
    trust). Bounded by TcpNet.MAX_FRAME — shard/rebalance chunks the
    verified subset before streaming it to the target group."""

    endpoint: str
    nonce: int


@dataclass(frozen=True)
class ShardExport:
    nonce: int
    entries: dict


@dataclass(frozen=True)
class ShardPruneRequest:
    """Controller -> agent: drop repository entries the group no longer
    owns under its CURRENT fencing map (post-activation cleanup)."""

    nonce: int


@dataclass(frozen=True)
class ShardPruned:
    nonce: int
    dropped: int


# --------------------------------------------------------------------------
# Atlas geo plane: read leases + region-local reads (dds_tpu/geo)
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class LeaseRequest:
    """Proxy -> the replica homed in the proxy's region: grant (or renew)
    the region's read lease on yourself for `ttl` seconds. Signed with
    the ABD MAC over the (region, ttl) manifest so only quorum members /
    secret holders can move the group into pinned-quorum geometry (a
    forged grant would be a free availability attack: every quorum
    would wait on the forger's chosen replica)."""

    region: str
    ttl: float
    nonce: int
    signature: bytes


@dataclass(frozen=True)
class LeaseGrant:
    """Replica -> proxy: the lease is installed in the group's shared
    LeaseTable (ok=True) or refused (ok=False: no table wired, or the
    replica is not this region's designated holder). `token` is the
    table-minted HMAC capability LocalRead must echo; `expires` is in
    the GRANTING side's clock — the proxy derives its own renew horizon
    from `ttl` it requested, never from a remote clock."""

    region: str
    replica: str
    token: str
    expires: float
    ok: bool
    nonce: int
    signature: bytes


@dataclass(frozen=True)
class LeaseRevoke:
    """Admin/supervisor -> any group replica: drop `region`'s lease from
    the shared table. Same manifest-MAC bar as LeaseRequest. The current
    holder finds out the hard way (its next LocalRead is refused), which
    is exactly the fallback path the client must survive anyway."""

    region: str
    nonce: int
    signature: bytes


@dataclass(frozen=True)
class LocalRead:
    """Proxy -> lease-holding replica: answer `key` from local state
    under the lease capability `token` — no quorum round. Only valid
    while the table says (region, replica, token) is the active lease;
    anything else is refused with ok=False so the proxy falls back to a
    full cross-region quorum read instead of timing out."""

    key: str
    region: str
    token: str
    nonce: int
    signature: bytes
    epoch: int = -1


@dataclass(frozen=True)
class LocalReadReply:
    tag: Optional[ABDTag]
    key: str
    value: Optional[DDSSet]
    ok: bool
    nonce: int
    signature: bytes


# --------------------------------------------------------------------------
# Panopticon fleet telemetry (dds_tpu/obs/panopticon)
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class TelemetryBatch:
    """Shipper -> collector: one batch of fleet telemetry from a non-proxy
    process. `spans` is a list of completed span trees (each a list of
    `utils.trace.event_dict` dicts), `incidents` flight-recorder index
    entries, `metrics_text` the source's full Prometheus exposition, and
    `slo` its SloEngine report. `mac` is HMAC-SHA256 over the canonical
    JSON of the payload with the fleet telemetry secret — an extra
    integrity layer above the frame MAC, so a collector can accept
    batches relayed through untrusted hops. Integrity only: a Byzantine
    HOST can still sign lies about its own stats (DEPLOY.md "Fleet
    observability"). The list/dict fields ride opaque on purpose — span
    meta is workload-derived and must never decode as protocol objects."""

    host: str
    role: str
    shard: str
    seq: int
    ts: float
    spans: list
    incidents: list
    metrics_text: str
    slo: dict
    dropped: int          # spool drops at the SOURCE since process start
    mac: bytes
    # Atlas region label of the shipping process ("" = unplaced). Covered
    # by the payload MAC like every other field; the collector surfaces
    # it on federated metrics and incident correlation.
    region: str = ""


@dataclass(frozen=True)
class TelemetryAck:
    """Collector -> shipper: batch `seq` landed (ok=False = bad MAC or
    malformed — the shipper counts rejects but never retries a reject:
    a batch the collector refuses once will be refused again)."""

    seq: int
    ok: bool
    error: str = ""


# --------------------------------------------------------------------------
# fault injection backdoor (malicious/MaliciousAttack.scala:34)
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class Compromise:
    pass


@dataclass(frozen=True)
class Crash:
    """Fault-injection control: the node tears its endpoint off the
    transport and goes silent — the PoisonPill analogue that also works
    across the TCP fabric (the reference's Trudy holds in-process
    ActorRefs, `Trudy.scala:14-32`). A harness backdoor like Compromise,
    not a production message."""


# --------------------------------------------------------------------------
# serialization: tagged canonical JSON
# --------------------------------------------------------------------------

_TYPES = {
    cls.__name__: cls
    for cls in (
        IRead, IWrite, IReadReply, IWriteReply, Envelope,
        ReadTag, TagReply, Write, WriteAck, Read, ReadReply,
        ReadTagBatch, TagBatchReply,
        Suspect, Awake, State, Sleep, Complying, Kill,
        Redeploy, Redeployed, RequestReplicas, ActiveReplicas, Compromise,
        Crash,
        StateDigestRequest, StateDigest, SleepBegin, StateChunk,
        MerkleRootRequest, MerkleRoot, MerkleBucketRequest, MerkleBuckets,
        MerkleKeysRequest, MerkleKeys, RepairRequest, RepairReply,
        WrongShard, ShardMigrateBegin, ShardMigrateAck,
        ShardMapInstall, ShardMapActivate, ShardMapAck,
        ShardExportRequest, ShardExport, ShardPruneRequest, ShardPruned,
        LeaseRequest, LeaseGrant, LeaseRevoke, LocalRead, LocalReadReply,
        TelemetryBatch, TelemetryAck,
    )
}


def _enc(v):
    if isinstance(v, bytes):
        return {"__b64__": base64.b64encode(v).decode()}
    if isinstance(v, ABDTag):
        return {"__tag__": [v.seq, v.id]}
    if type(v) in _TYPES.values():
        return to_dict(v)
    return v


def _dec(v):
    if isinstance(v, dict):
        if "__b64__" in v:
            return base64.b64decode(v["__b64__"])
        if "__tag__" in v:
            return ABDTag(int(v["__tag__"][0]), str(v["__tag__"][1]))
        if "__msg__" in v:
            return from_dict(v)
    return v


def to_dict(msg) -> dict:
    # element-wise coding applies ONLY to the tuple-typed protocol fields
    # (tag vectors / key tuples of the batch messages). Stored set contents
    # (list fields) stay opaque: recursing into them would let a crafted
    # client column value (e.g. {"__msg__": ...}) be (de)coded as a protocol
    # object inside the receive path, before any MAC validation.
    d = {"__msg__": type(msg).__name__}
    for f in fields(msg):
        v = getattr(msg, f.name)
        if f.type == "tuple" and isinstance(v, (list, tuple)):
            d[f.name] = [_enc(x) for x in v]
        else:
            d[f.name] = _enc(v)
    return d


def from_dict(d: dict):
    cls = _TYPES[d["__msg__"]]
    kwargs = {}
    for f in fields(cls):
        v = d[f.name]
        if f.type == "tuple" and isinstance(v, list):  # JSON has no tuples
            v = tuple(_dec(x) for x in v)
        else:
            v = _dec(v)
        kwargs[f.name] = v
    return cls(**kwargs)


def dumps(msg) -> bytes:
    return json.dumps(to_dict(msg), separators=(",", ":")).encode()


def loads(raw: bytes):
    return from_dict(json.loads(raw))
