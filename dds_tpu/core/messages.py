"""Wire messages for the BFT-ABD protocol, supervisor, and proxy contract.

Counterpart of the reference's three API files (`dds/api/ABDAPI.scala`,
`InternalAPI.scala`, `SupervisorAPI.scala`) and the small data models under
`dds/core/models/`. Serialization is tagged canonical JSON (language-neutral)
instead of Java/Akka serialization.

A "set" (the stored value) is a plain JSON list or None; tags order writes.
Tag ordering deviation (documented per SURVEY.md §7): the reference breaks
seq ties arbitrarily (`BFTABDNode.scala:185-188`); we order by (seq, id) —
the standard ABD total order — so write-back is deterministic.
"""

from __future__ import annotations

import base64
import json
from dataclasses import dataclass, fields
from typing import Any, Optional

DDSSet = list  # a stored record: JSON-safe list of column values


@dataclass(frozen=True, order=True)
class ABDTag:
    seq: int
    id: str


# --------------------------------------------------------------------------
# proxy <-> replica intermediate API (InternalAPI.scala)
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class IRead:
    key: str


@dataclass(frozen=True)
class IWrite:
    key: str
    set: Optional[DDSSet]


@dataclass(frozen=True)
class IReadReply:
    key: str
    set: Optional[DDSSet]


@dataclass(frozen=True)
class IWriteReply:
    key: str


@dataclass(frozen=True)
class Envelope:
    call: Any          # one of the I* messages above
    nonce: int
    signature: bytes


# --------------------------------------------------------------------------
# replica <-> replica ABD protocol (ABDAPI.scala)
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class ReadTag:
    key: str
    nonce: int


@dataclass(frozen=True)
class TagReply:
    tag: ABDTag
    key: str
    value: Optional[DDSSet]
    signature: bytes
    nonce: int


@dataclass(frozen=True)
class Write:
    tag: ABDTag
    key: str
    value: Optional[DDSSet]
    signature: bytes
    nonce: int


@dataclass(frozen=True)
class WriteAck:
    key: str
    nonce: int


@dataclass(frozen=True)
class Read:
    key: str
    nonce: int


@dataclass(frozen=True)
class ReadReply:
    tag: ABDTag
    key: str
    value: Optional[DDSSet]
    signature: bytes
    nonce: int


# --------------------------------------------------------------------------
# supervisor protocol (SupervisorAPI.scala)
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class Suspect:
    replica: str       # endpoint of the suspected replica
    nonce: int


@dataclass(frozen=True)
class Awake:
    pass


@dataclass(frozen=True)
class State:
    data: dict         # key -> {"tag": [seq, id], "value": set|None}
    nonces: list[int]


@dataclass(frozen=True)
class Sleep:
    data: dict
    nonces: list[int]


@dataclass(frozen=True)
class Complying:
    pass


@dataclass(frozen=True)
class Kill:
    """Control message: hard-restart the replica with empty state.

    The reference uses Akka `Kill` + the guardian's restart strategy
    (`BFTSupervisor.scala:115`, `BFTSupervisorStrategy.scala:8-10`); our
    transport delivers an explicit control message the node host honors.
    """


@dataclass(frozen=True)
class RequestReplicas:
    pass


@dataclass(frozen=True)
class ActiveReplicas:
    replicas: list[str]


# --------------------------------------------------------------------------
# fault injection backdoor (malicious/MaliciousAttack.scala:34)
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class Compromise:
    pass


# --------------------------------------------------------------------------
# serialization: tagged canonical JSON
# --------------------------------------------------------------------------

_TYPES = {
    cls.__name__: cls
    for cls in (
        IRead, IWrite, IReadReply, IWriteReply, Envelope,
        ReadTag, TagReply, Write, WriteAck, Read, ReadReply,
        Suspect, Awake, State, Sleep, Complying, Kill,
        RequestReplicas, ActiveReplicas, Compromise,
    )
}


def _enc(v):
    if isinstance(v, bytes):
        return {"__b64__": base64.b64encode(v).decode()}
    if isinstance(v, ABDTag):
        return {"__tag__": [v.seq, v.id]}
    if type(v) in _TYPES.values():
        return to_dict(v)
    return v


def _dec(v):
    if isinstance(v, dict):
        if "__b64__" in v:
            return base64.b64decode(v["__b64__"])
        if "__tag__" in v:
            return ABDTag(int(v["__tag__"][0]), str(v["__tag__"][1]))
        if "__msg__" in v:
            return from_dict(v)
    return v


def to_dict(msg) -> dict:
    d = {"__msg__": type(msg).__name__}
    for f in fields(msg):
        d[f.name] = _enc(getattr(msg, f.name))
    return d


def from_dict(d: dict):
    cls = _TYPES[d["__msg__"]]
    kwargs = {f.name: _dec(d[f.name]) for f in fields(cls)}
    return cls(**kwargs)


def dumps(msg) -> bytes:
    return json.dumps(to_dict(msg), separators=(",", ":")).encode()


def loads(raw: bytes):
    return from_dict(json.loads(raw))
