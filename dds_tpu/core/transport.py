"""Async message transports for the replicated core.

The reference rides Akka remoting (netty SSL TCP) for every actor-to-actor
hop (`dds-system.conf:18-58`). The TPU-native design keeps control-plane
messaging on the CPU in plain asyncio (quorum logic is control flow, not
math — SURVEY.md §5.8) with two interchangeable transports:

- `InMemoryNet`: zero-copy in-process delivery with per-link fault hooks
  (drop / delay / duplicate / corrupt) — the unit/property-test fabric the
  reference never had, and the single-process deployment fabric (the
  reference also runs its whole 9-replica quorum in one process when the
  topology says so, SURVEY.md §4).
- `TcpNet`: length-prefixed frames over asyncio TCP, optional TLS — the
  multi-host fabric.

Addresses are opaque strings ("replica-3", "host:port/replica-3"). Delivery
is fire-and-forget and unordered, like actor tell; all integrity comes from
the HMAC layer inside the messages.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable, Optional

from dds_tpu.core import messages as M
from dds_tpu.obs import context as obs_context
from dds_tpu.utils.tasks import supervised_task

log = logging.getLogger("dds.transport")

Handler = Callable[[str, object], Awaitable[None]]


class Transport:
    """Interface: register local endpoints, send to any endpoint."""

    def register(self, addr: str, handler: Handler) -> None:
        raise NotImplementedError

    def unregister(self, addr: str) -> None:
        raise NotImplementedError

    def send(self, src: str, dest: str, msg: object) -> None:
        raise NotImplementedError

    def has_endpoint(self, addr: str) -> bool:
        raise NotImplementedError


class InMemoryNet(Transport):
    def __init__(self):
        self._handlers: dict[str, Handler] = {}
        # test hooks: (src, dest) or dest -> async fn(msg) -> msg | None (drop)
        self.link_filters: dict[object, Callable] = {}
        self._tasks: set[asyncio.Task] = set()

    def register(self, addr: str, handler: Handler) -> None:
        self._handlers[addr] = handler

    def unregister(self, addr: str) -> None:
        self._handlers.pop(addr, None)

    def has_endpoint(self, addr: str) -> bool:
        return addr in self._handlers

    def send(self, src: str, dest: str, msg: object) -> None:
        task = supervised_task(self._deliver(src, dest, msg),
                               name=f"inmem.deliver:{dest}")
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _deliver(self, src: str, dest: str, msg: object) -> None:
        for key in ((src, dest), dest):
            f = self.link_filters.get(key)
            if f is not None:
                msg = await f(msg)
                if msg is None:
                    return
        handler = self._handlers.get(dest)
        if handler is None:
            log.debug("drop %s -> %s (no endpoint): %s", src, dest, type(msg).__name__)
            return
        try:
            await handler(src, msg)
        except Exception:
            log.exception("handler error at %s for %s", dest, type(msg).__name__)

    async def quiesce(self) -> None:
        """Wait until all in-flight deliveries (and their follow-ups) drain."""
        while True:
            pending = [t for t in self._tasks if not t.done()]
            if not pending:
                break
            await asyncio.gather(*pending, return_exceptions=True)
            await asyncio.sleep(0)  # let done-callbacks prune the task set


class TcpNet(Transport):
    """Multi-host transport: frames are 4-byte big-endian length + JSON.

    Each frame carries (src, dest, payload) and, when `frame_secret` is set,
    an HMAC-SHA256 over the canonical frame — the channel-authentication
    role the reference delegates to mutual-TLS Akka remoting
    (`dds-system.conf:18-58`). Without it, a keyless network attacker could
    spoof the `src` field and forge sender-keyed quorum votes (WriteAck,
    Suspect). TLS contexts can be layered on top/instead.

    One listening socket per host serves all endpoints registered on it;
    outbound connections are cached per destination host.
    """

    def __init__(
        self,
        host: str,
        port: int,
        ssl_server=None,
        ssl_client=None,
        frame_secret: bytes | None = None,
        node_key=None,
        peer_keys: dict | None = None,
        advertise: str = "",
    ):
        self.host, self.port = host, port
        # The address peers use to reach/name this process. A process that
        # binds 0.0.0.0 (or binds an IP while peers address it by hostname)
        # must advertise the peer-visible address, or every signed inbound
        # frame fails the dest-host check below and the fabric silently
        # drops all traffic. "host" or "host:port"; empty = the bind
        # address.
        self._advertise = advertise
        self._handlers: dict[str, Handler] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: dict[str, asyncio.StreamWriter] = {}
        self._ssl_server, self._ssl_client = ssl_server, ssl_client
        self._frame_secret = frame_secret
        # per-node identity (utils/nodeauth): node_key is THIS process's
        # Ed25519 private key; peer_keys maps "host:port" -> public key.
        # When peer_keys is set, inbound frames are accepted only if their
        # signature verifies against the claimed src's registered key —
        # the sender-authenticity layer the sender-keyed quorum votes need
        # (a shared frame secret or cluster-wide TLS cert only proves
        # membership, not which member).
        self._node_key = node_key
        self._peer_keys = peer_keys
        # signed frames carry a strictly increasing counter (seeded with
        # wall time so process restarts keep increasing); receivers track
        # the max seen per src host:port and drop non-increasing frames —
        # without it a captured signed frame (e.g. a Kill) could be
        # replayed verbatim. Sound because each sender->receiver pair
        # rides ONE cached FIFO connection. Known limits (documented, not
        # closed): the receiver-side counter state is in-memory, so frames
        # captured before a receiver RESTART can be replayed into the
        # fresh process until the genuine sender next transmits; and a
        # sender whose clock steps far backwards across ITS restart sends
        # below peers' recorded max until the clock catches up. Pair with
        # intranet TLS (which closes on-path capture entirely) where those
        # windows matter.
        import itertools
        import time as _time

        self._send_ctr = itertools.count(_time.time_ns())
        self._seen_ctr: dict[str, int] = {}
        self._lock = asyncio.Lock()

    @staticmethod
    def _frame_body(src: str, dest: str, payload: dict, ctr=None) -> bytes:
        import json

        return json.dumps([src, dest, ctr, payload], sort_keys=True).encode()

    def _frame_mac(self, body: bytes) -> str:
        import hashlib
        import hmac as hmac_mod

        return hmac_mod.new(self._frame_secret, body, hashlib.sha256).hexdigest()

    # endpoint addresses look like "host:port/name"
    @staticmethod
    def split(addr: str) -> tuple[str, int, str]:
        hostport, name = addr.split("/", 1)
        host, port = hostport.rsplit(":", 1)
        return host, int(port), name

    @property
    def advertised(self) -> str:
        """This process's peer-visible "host:port" (see `advertise`)."""
        if self._advertise:
            if ":" in self._advertise:
                return self._advertise
            return f"{self._advertise}:{self.port}"
        return f"{self.host}:{self.port}"

    def local_addr(self, name: str) -> str:
        return f"{self.advertised}/{name}"

    def register(self, addr: str, handler: Handler) -> None:
        _, _, name = self.split(addr) if "/" in addr else (None, None, addr)
        self._handlers[name] = handler

    def unregister(self, addr: str) -> None:
        _, _, name = self.split(addr) if "/" in addr else (None, None, addr)
        self._handlers.pop(name, None)

    def has_endpoint(self, addr: str) -> bool:
        name = addr.rsplit("/", 1)[-1]
        return name in self._handlers

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._serve, self.host, self.port, ssl=self._ssl_server
        )
        if self.port == 0:  # resolve an OS-assigned port for local_addr()
            self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        # close outbound connections first: the EOF unblocks server-side
        # _serve loops, letting wait_closed() complete
        for w in self._conns.values():
            w.close()
        self._conns.clear()
        if self._server:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2)
            except asyncio.TimeoutError:
                pass

    # max inbound frame (reference: akka maximum-frame-size = 30 MB,
    # dds-system.conf:58): a peer declaring a huge length must not make
    # the receiver buffer it
    MAX_FRAME = 32 * 1024 * 1024

    async def _serve(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            while True:
                hdr = await reader.readexactly(4)
                size = int.from_bytes(hdr, "big")
                if size > self.MAX_FRAME:
                    log.warning(
                        "dropping connection from %s: %d-byte frame declared",
                        writer.get_extra_info("peername"), size,
                    )
                    break
                frame = await reader.readexactly(size)
                import json

                # Per-frame decode must not tear down the shared connection:
                # a malformed frame (or one from a peer speaking a newer
                # codec during a rolling upgrade) is logged and skipped —
                # killing the loop here would silently drop every queued
                # frame behind it from the same peer.
                try:
                    obj = json.loads(frame)
                    src, dest, payload = obj["src"], obj["dest"], obj["msg"]
                    if not isinstance(src, str) or not isinstance(dest, str):
                        raise ValueError("non-string src/dest")
                except Exception as e:
                    log.warning(
                        "dropping undecodable frame from %s: %s",
                        writer.get_extra_info("peername"), e,
                    )
                    continue
                body = None
                if self._frame_secret is not None or self._peer_keys is not None:
                    body = self._frame_body(src, dest, payload, obj.get("ctr"))
                if self._frame_secret is not None:
                    import hmac as hmac_mod

                    if not hmac_mod.compare_digest(
                        obj.get("mac", ""), self._frame_mac(body)
                    ):
                        log.warning("dropping frame with bad MAC (src claims %s)", src)
                        continue
                if self._peer_keys is not None:
                    src_host = src.split("/", 1)[0]
                    pub = self._peer_keys.get(src_host)
                    try:
                        if pub is None:
                            raise ValueError("unregistered src host")
                        # the signed dest must name THIS process (by its
                        # ADVERTISED address): endpoint names repeat across
                        # hosts (proxy-0, nodehost), so a frame captured on
                        # the wire to host A must not verify and dispatch
                        # on host B
                        if "/" in dest and dest.split("/", 1)[0] != self.advertised:
                            raise ValueError("frame destined for another host")
                        pub.verify(bytes.fromhex(obj.get("sig", "")), body)
                        ctr = int(obj["ctr"])
                        if ctr <= self._seen_ctr.get(src_host, -1):
                            raise ValueError("replayed frame counter")
                        self._seen_ctr[src_host] = ctr
                    except Exception:
                        log.warning(
                            "dropping frame with bad/missing node signature, "
                            "wrong dest host, or replayed counter "
                            "(src claims %s)", src,
                        )
                        continue
                name = dest.split("/", 1)[1] if "/" in dest else dest
                handler = self._handlers.get(name)
                if handler is not None:
                    try:
                        msg = M.from_dict(payload)
                    except Exception as e:
                        log.warning(
                            "dropping frame with undecodable payload from "
                            "%s: %s", src, e,
                        )
                        continue
                    # restore the sender's trace context (frame `tc`, see
                    # _send) so spans recorded by the handler join the
                    # originating request's trace tree across the TCP hop.
                    # Observability metadata only — outside the MAC, and a
                    # malformed field degrades to an unlinked span, never
                    # a dropped message.
                    tc = obs_context.from_wire(obj.get("tc"))
                    if tc is not None:
                        supervised_task(
                            self._handle_traced(handler, tc, src, msg),
                            name=f"tcp.handle:{src}",
                        )
                    else:
                        supervised_task(handler(src, msg),
                                        name=f"tcp.handle:{src}")
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    @staticmethod
    async def _handle_traced(handler, tc, src: str, msg) -> None:
        token = obs_context.attach(tc)
        try:
            await handler(src, msg)
        finally:
            obs_context.detach(token)

    def send(self, src: str, dest: str, msg: object) -> None:
        supervised_task(self._send(src, dest, msg),
                        name=f"tcp.send:{dest}")

    async def _send(self, src: str, dest: str, msg: object) -> None:
        import json
        import time

        host, port, _ = self.split(dest)
        conn_key = f"{host}:{port}"
        try:
            async with self._lock:
                w = self._conns.get(conn_key)
                if w is None or w.is_closing():
                    _, w = await asyncio.open_connection(host, port, ssl=self._ssl_client)
                    self._conns[conn_key] = w
            t_ser = time.perf_counter()
            payload = M.to_dict(msg)
            obj = {"src": src, "dest": dest, "msg": payload}
            # trace-context propagation (ensure_future copied the caller's
            # contextvars into this task, so current() is the sender's span)
            tc = obs_context.to_wire()
            if tc is not None:
                obj["tc"] = tc
            if self._frame_secret is not None or self._node_key is not None:
                ctr = next(self._send_ctr) if self._node_key is not None else None
                if ctr is not None:
                    obj["ctr"] = ctr
                body = self._frame_body(src, dest, payload, ctr)
                if self._frame_secret is not None:
                    obj["mac"] = self._frame_mac(body)
                if self._node_key is not None:
                    obj["sig"] = self._node_key.sign(body).hex()
            frame = json.dumps(obj).encode()
            if tc is not None:
                # Chronoscope's serialize stage: dict-encode + json + frame
                # MAC/signature, attributed to the SENDER's span (tc is only
                # non-None inside one)
                from dds_tpu.utils.trace import tracer

                cur = obs_context.current()
                tracer.record(
                    "net.serialize",
                    (time.perf_counter() - t_ser) * 1e3,
                    _ctx=obs_context.child(cur) if cur is not None else None,
                    bytes=len(frame), dest=dest.rsplit("/", 1)[-1],
                )
            if len(frame) > self.MAX_FRAME:
                # symmetric with the receive bound: sending it anyway would
                # get the shared cached connection killed at the receiver,
                # silently losing queued frames behind it
                log.error(
                    "refusing to send %d-byte frame %s -> %s (MAX_FRAME %d)",
                    len(frame), src, dest, self.MAX_FRAME,
                )
                return
            t_drain = time.perf_counter()
            w.write(len(frame).to_bytes(4, "big") + frame)
            await w.drain()
            from dds_tpu.obs.metrics import metrics

            metrics.observe(
                "dds_net_drain_seconds", time.perf_counter() - t_drain,
                help="TCP send-buffer drain wait (backpressure signal)",
            )
        except OSError:
            log.warning("send failed %s -> %s", src, dest)
            self._conns.pop(conn_key, None)
