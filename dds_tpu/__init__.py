"""dds_tpu — a TPU-native Dependable Data Storage framework.

A from-scratch re-design of the capabilities of
``fmiguelgodinho/dependable-data-storage-csd2017`` (a Byzantine fault-tolerant,
replicated, CryptDB-style encrypted key->set store) built TPU-first:

- tier 0: batched big-integer limb arithmetic + Montgomery modmul/modexp as
  JAX/Pallas kernels (``dds_tpu.ops``)
- tier 1: homomorphic / property-preserving encryption schemes
  (``dds_tpu.models``) with pluggable cpu / tpu backends
- tier 2: asyncio BFT-ABD replicated core (``dds_tpu.core``)
- tier 3: REST proxy / encrypted query engine (``dds_tpu.http``)
- tier 4: supervisor control plane (``dds_tpu.core.supervisor``)
- tier 5: workload harness, bench client, fault injector
  (``dds_tpu.clt``, ``dds_tpu.malicious``)

The reference system is Scala/Akka; nothing here is a translation — the
compute-heavy homomorphic arithmetic is re-designed as fixed-shape batched
limb tensors for the TPU VPU/MXU, and the replication control plane is
asyncio + HMAC-framed transports.
"""

__version__ = "0.1.0"


def _setup_jax_compilation_cache() -> None:
    """Enable JAX's persistent compilation cache for the whole framework.

    The tier-0 kernels compile one executable per (modulus limb count,
    batch shape); a cold proxy/client process otherwise recompiles every
    shape (~20-40 s each on tunneled TPU platforms). Set via environment
    variables (read by jax at ITS import — no jax import cost here for
    host-only consumers). Opt out with DDS_JAX_CACHE=off; point elsewhere
    with DDS_JAX_CACHE=/path.
    """
    import os

    val = os.environ.get("DDS_JAX_CACHE", "")
    if val.strip().lower() in ("0", "off", "false", "no"):
        return
    path = val or os.path.join(
        os.path.expanduser("~"), ".cache", "dds_tpu_jax"
    )
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", path)
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1.0")
    # If a consumer (or the interpreter's sitecustomize) imported jax before
    # us, jax has already read its env; apply the setting via jax.config so
    # the persistent cache is enabled regardless of import order.
    import sys

    if "jax" in sys.modules:
        import jax

        jax.config.update(
            "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
        )


def _honor_jax_platforms() -> None:
    """Re-assert the JAX_PLATFORMS env var when jax was imported early.

    Some environments (e.g. a sitecustomize that registers a PJRT plugin for
    every interpreter) import jax before user code runs and re-register
    accelerator platforms, so a parent process's `JAX_PLATFORMS=cpu` is
    silently ignored — and the first `jax.default_backend()` then initializes
    the accelerator plugin, which can hang outright when the device link is
    down. Applying the env var through jax.config restores the documented
    contract: JAX_PLATFORMS=cpu means CPU, always.
    """
    import os
    import sys

    val = os.environ.get("JAX_PLATFORMS", "").strip()
    if val and "jax" in sys.modules:
        import jax

        jax.config.update("jax_platforms", val)


_setup_jax_compilation_cache()
_honor_jax_platforms()
