"""dds_tpu — a TPU-native Dependable Data Storage framework.

A from-scratch re-design of the capabilities of
``fmiguelgodinho/dependable-data-storage-csd2017`` (a Byzantine fault-tolerant,
replicated, CryptDB-style encrypted key->set store) built TPU-first:

- tier 0: batched big-integer limb arithmetic + Montgomery modmul/modexp as
  JAX/Pallas kernels (``dds_tpu.ops``)
- tier 1: homomorphic / property-preserving encryption schemes
  (``dds_tpu.models``) with pluggable cpu / tpu backends
- tier 2: asyncio BFT-ABD replicated core (``dds_tpu.core``)
- tier 3: REST proxy / encrypted query engine (``dds_tpu.http``)
- tier 4: supervisor control plane (``dds_tpu.core.supervisor``)
- tier 5: workload harness, bench client, fault injector
  (``dds_tpu.clt``, ``dds_tpu.malicious``)

The reference system is Scala/Akka; nothing here is a translation — the
compute-heavy homomorphic arithmetic is re-designed as fixed-shape batched
limb tensors for the TPU VPU/MXU, and the replication control plane is
asyncio + HMAC-framed transports.
"""

__version__ = "0.1.0"
