"""JSON wire models, counterpart of `dds/http/DDSJsonProtocol.scala:7-10`.

Same shapes the reference marshals with spray-json:

    DDSSet          {"contents": [...]}
    DDSItem         {"value": x}
    DDSItemTriplet  {"value1": x, "value2": y, "value3": z}
    DDSValueResult  {"result": x}
    DDSKeysResult   {"keyset": ["...", ...]}

Values are JSON scalars (int / str / bool / null), like the reference's
`AnyJsonFormat`.
"""

from __future__ import annotations


def dds_set(contents: list) -> dict:
    return {"contents": contents}


def value_result(result) -> dict:
    return {"result": result}


def keys_result(keyset: list[str]) -> dict:
    return {"keyset": keyset}


def parse_set(obj) -> list:
    if not isinstance(obj, dict) or not isinstance(obj.get("contents"), list):
        raise ValueError("expected {'contents': [...]}")
    return obj["contents"]


def parse_item(obj):
    if not isinstance(obj, dict) or "value" not in obj:
        raise ValueError("expected {'value': ...}")
    return obj["value"]


def parse_triplet(obj) -> tuple:
    if not isinstance(obj, dict) or not all(f"value{i}" in obj for i in (1, 2, 3)):
        raise ValueError("expected {'value1','value2','value3'}")
    return obj["value1"], obj["value2"], obj["value3"]


def parse_range(obj) -> tuple[int, int]:
    """POST /Range body: {'value1': lo, 'value2': hi} — inclusive int
    bounds (decimal strings accepted, like every Search* item)."""
    if not isinstance(obj, dict) or not all(f"value{i}" in obj for i in (1, 2)):
        raise ValueError("expected {'value1': lo, 'value2': hi}")
    return int(obj["value1"]), int(obj["value2"])


def parse_keys(obj) -> list[str]:
    if not isinstance(obj, dict) or not isinstance(obj.get("keyset"), list):
        raise ValueError("expected {'keyset': [...]}")
    return [str(k) for k in obj["keyset"]]


# ---- Prism analytics wire shapes (POST /MatVec, /WeightedSum, /GroupBySum)


def _parse_weight(x) -> int:
    # bool is an int subclass; a JSON true/false weight is a client bug,
    # not a 1/0 — reject it loudly. Decimal strings are accepted so
    # clients in integer-poor ecosystems can ship big weights losslessly.
    if isinstance(x, int) and not isinstance(x, bool):
        return x
    if isinstance(x, str):
        try:
            return int(x)
        except ValueError:
            raise ValueError(f"non-integer weight {x!r}") from None
    raise ValueError("weights must be integers (or decimal strings)")


def parse_weight_matrix(obj) -> list[list[int]]:
    if (
        not isinstance(obj, dict)
        or not isinstance(obj.get("weights"), list)
        or not obj["weights"]
    ):
        raise ValueError("expected {'weights': [[...], ...]}")
    rows = obj["weights"]
    if not all(isinstance(r, list) for r in rows):
        raise ValueError("'weights' must be a list of weight rows")
    return [[_parse_weight(x) for x in r] for r in rows]


def parse_weight_row(obj) -> list[int]:
    if (
        not isinstance(obj, dict)
        or not isinstance(obj.get("weights"), list)
        or not obj["weights"]
    ):
        raise ValueError("expected {'weights': [...]}")
    return [_parse_weight(x) for x in obj["weights"]]


def parse_groups(obj) -> dict[str, list[str]]:
    if not isinstance(obj, dict) or not isinstance(obj.get("groups"), dict):
        raise ValueError("expected {'groups': {label: [keys...]}}")
    out: dict[str, list[str]] = {}
    for label, keys in obj["groups"].items():
        if not isinstance(keys, list) or not all(
            isinstance(k, str) for k in keys
        ):
            raise ValueError(f"group {label!r} must list record-key strings")
        out[str(label)] = keys
    return out
