"""JSON wire models, counterpart of `dds/http/DDSJsonProtocol.scala:7-10`.

Same shapes the reference marshals with spray-json:

    DDSSet          {"contents": [...]}
    DDSItem         {"value": x}
    DDSItemTriplet  {"value1": x, "value2": y, "value3": z}
    DDSValueResult  {"result": x}
    DDSKeysResult   {"keyset": ["...", ...]}

Values are JSON scalars (int / str / bool / null), like the reference's
`AnyJsonFormat`.
"""

from __future__ import annotations


def dds_set(contents: list) -> dict:
    return {"contents": contents}


def value_result(result) -> dict:
    return {"result": result}


def keys_result(keyset: list[str]) -> dict:
    return {"keyset": keyset}


def parse_set(obj) -> list:
    if not isinstance(obj, dict) or not isinstance(obj.get("contents"), list):
        raise ValueError("expected {'contents': [...]}")
    return obj["contents"]


def parse_item(obj):
    if not isinstance(obj, dict) or "value" not in obj:
        raise ValueError("expected {'value': ...}")
    return obj["value"]


def parse_triplet(obj) -> tuple:
    if not isinstance(obj, dict) or not all(f"value{i}" in obj for i in (1, 2, 3)):
        raise ValueError("expected {'value1','value2','value3'}")
    return obj["value1"], obj["value2"], obj["value3"]


def parse_keys(obj) -> list[str]:
    if not isinstance(obj, dict) or not isinstance(obj.get("keyset"), list):
        raise ValueError("expected {'keyset': [...]}")
    return [str(k) for k in obj["keyset"]]
