"""Tiny asyncio HTTP/1.1 server + client.

The reference rides akka-http with mutual-TLS HTTPS
(`dds/http/DDSRestServer.scala:94-148`). The framework keeps zero external
dependencies: this module implements just enough HTTP/1.1 for the 23 REST
routes — request-line + headers + Content-Length bodies, query strings,
keep-alive — over asyncio streams, with optional `ssl.SSLContext`s for TLS
(including mutual auth) on both ends.
"""

from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Optional
from urllib.parse import parse_qsl, unquote, urlsplit

MAX_BODY = 64 * 1024 * 1024


@dataclass
class Request:
    method: str
    path: str            # decoded path, no query string
    query: dict[str, str]
    headers: dict[str, str]
    body: bytes

    def json(self):
        return json.loads(self.body) if self.body else None


@dataclass
class Response:
    status: int = 200
    body: bytes = b""
    content_type: str = "text/plain; charset=utf-8"
    headers: dict[str, str] = field(default_factory=dict)

    @staticmethod
    def json(obj, status: int = 200) -> "Response":
        return Response(status, json.dumps(obj).encode(), "application/json")

    @staticmethod
    def text(s: str, status: int = 200) -> "Response":
        return Response(status, s.encode())


_REASONS = {
    200: "OK", 204: "No Content", 304: "Not Modified",
    400: "Bad Request", 404: "Not Found",
    405: "Method Not Allowed", 413: "Payload Too Large",
    429: "Too Many Requests",
    500: "Internal Server Error", 503: "Service Unavailable",
}

Handler = Callable[[Request], Awaitable[Response]]


class HttpServer:
    """`handler_timeout` (seconds, 0 = off) is the transport-level backstop
    of the deadline story: a handler that somehow outlives the REST layer's
    own budget is cancelled and the client gets 503 + Retry-After instead
    of a silently wedged connection."""

    def __init__(self, host: str, port: int, handler: Handler, ssl_context=None,
                 handler_timeout: float = 0.0):
        self.host, self.port = host, port
        self.handler = handler
        self.ssl_context = ssl_context
        self.handler_timeout = handler_timeout
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._serve, self.host, self.port, ssl=self.ssl_context
        )
        if self.port == 0:  # resolve OS-assigned port
            self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2)
            except asyncio.TimeoutError:
                pass

    async def _serve(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            while True:
                line = await reader.readline()
                if not line or line in (b"\r\n", b"\n"):
                    break
                try:
                    method, target, _ = line.decode().split(" ", 2)
                    headers: dict[str, str] = {}
                    while True:
                        h = await reader.readline()
                        if h in (b"\r\n", b"\n", b""):
                            break
                        name, _, val = h.decode().partition(":")
                        headers[name.strip().lower()] = val.strip()
                    length = int(headers.get("content-length", 0))
                    if not (0 <= length <= MAX_BODY):
                        raise ValueError("bad content-length")
                except (ValueError, UnicodeDecodeError):
                    writer.write(
                        b"HTTP/1.1 400 Bad Request\r\nContent-Length: 0\r\n"
                        b"Connection: close\r\n\r\n"
                    )
                    await writer.drain()
                    break
                body = await reader.readexactly(length) if length else b""
                parts = urlsplit(target)
                req = Request(
                    method=method.upper(),
                    path=unquote(parts.path),
                    query=dict(parse_qsl(parts.query)),
                    headers=headers,
                    body=body,
                )
                try:
                    if self.handler_timeout > 0:
                        resp = await asyncio.wait_for(
                            self.handler(req), self.handler_timeout
                        )
                    else:
                        resp = await self.handler(req)
                except asyncio.TimeoutError:
                    resp = Response(
                        503, b"handler timed out",
                        headers={"Retry-After": "1"},
                    )
                except Exception:
                    import logging

                    logging.getLogger("dds.http").exception("handler error")
                    resp = Response(500)
                reason = _REASONS.get(resp.status, "Unknown")
                head = (
                    f"HTTP/1.1 {resp.status} {reason}\r\n"
                    f"Content-Type: {resp.content_type}\r\n"
                    f"Content-Length: {len(resp.body)}\r\n"
                )
                for k, v in resp.headers.items():
                    head += f"{k}: {v}\r\n"
                writer.write(head.encode() + b"\r\n" + resp.body)
                await writer.drain()
                if headers.get("connection", "").lower() == "close":
                    break
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()


async def http_request(
    host: str,
    port: int,
    method: str,
    target: str,
    body: bytes | None = None,
    content_type: str = "application/json",
    ssl_context=None,
    timeout: float = 30.0,
    headers: dict[str, str] | None = None,
) -> tuple[int, bytes]:
    """One-shot HTTP client request; returns (status, body)."""
    status, _, data = await http_request_full(
        host, port, method, target, body, content_type, ssl_context, timeout,
        headers,
    )
    return status, data


async def http_request_full(
    host: str,
    port: int,
    method: str,
    target: str,
    body: bytes | None = None,
    content_type: str = "application/json",
    ssl_context=None,
    timeout: float = 30.0,
    headers: dict[str, str] | None = None,
) -> tuple[int, dict, bytes]:
    """Like `http_request` but also returns the (lower-cased) response
    headers — callers inspecting Retry-After / degradation metadata.
    `headers` adds request headers (conditional gets, trace context,
    tenant attribution)."""

    async def go():
        reader, writer = await asyncio.open_connection(host, port, ssl=ssl_context)
        try:
            payload = body or b""
            extra = "".join(
                f"{k}: {v}\r\n" for k, v in (headers or {}).items()
            )
            head = (
                f"{method} {target} HTTP/1.1\r\n"
                f"Host: {host}:{port}\r\n"
                f"Content-Type: {content_type}\r\n"
                f"Content-Length: {len(payload)}\r\n"
                f"{extra}"
                f"Connection: close\r\n\r\n"
            )
            writer.write(head.encode() + payload)
            await writer.drain()
            status_line = await reader.readline()
            try:
                status = int(status_line.split()[1])
            except (IndexError, ValueError):
                raise ConnectionError(f"malformed status line: {status_line!r}")
            rheaders: dict[str, str] = {}
            while True:
                h = await reader.readline()
                if h in (b"\r\n", b"\n", b""):
                    break
                name, _, val = h.decode().partition(":")
                rheaders[name.strip().lower()] = val.strip()
            if "content-length" in rheaders:
                data = await reader.readexactly(int(rheaders["content-length"]))
            else:
                data = await reader.read()
            return status, rheaders, data
        finally:
            writer.close()

    return await asyncio.wait_for(go(), timeout)
