"""Tier-3 REST proxy: encrypted query engine over the BFT-ABD core."""

from dds_tpu.http.server import DDSRestServer, ProxyConfig  # noqa: F401
